"""Checkpoint save/load + inference export + reader/DataFeeder tests.

Mirrors reference tests: test_inference_model_io.py, reader decorator
tests, DataFeeder tests.
"""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io, layers
from paddle_tpu.data import (DataFeeder, batch, buffered, chain, compose,
                             dataset, firstn, map_readers, shuffle,
                             xmap_readers)


def test_save_load_persistables_roundtrip(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.fc(x, size=3)
        loss = layers.mean(y)
        fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
        w = main.all_parameters()[0]
        w_before = np.asarray(scope.find_var(w.name))
        io.save_persistables(exe, str(tmp_path), main)
        # clobber and reload
        scope.set_var(w.name, np.zeros_like(w_before))
        io.load_persistables(exe, str(tmp_path), main)
        np.testing.assert_allclose(np.asarray(scope.find_var(w.name)),
                                   w_before)
        # adam moments saved too
        assert scope.find_var(f"{w.name}.moment1") is not None


def test_save_load_inference_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = layers.data(name="x", shape=[4], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        out = layers.fc(h, size=2, act="softmax")
        lbl = layers.data(name="lbl", shape=[1], dtype="int64")
        loss = layers.mean(layers.cross_entropy(out, lbl))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        xv = np.random.RandomState(0).rand(3, 4).astype(np.float32)
        exe.run(main, feed={"x": xv, "lbl": np.zeros((3, 1), np.int64)},
                fetch_list=[loss])  # one train step
        test_prog = main.clone(for_test=True)
        (expected,) = exe.run(test_prog, feed={"x": xv},
                              fetch_list=[out.name])
        io.save_inference_model(str(tmp_path), ["x"], [out], exe, main)

    # fresh scope + fresh executor: the exported dir is self-contained
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor()
        prog, feed_names, fetch_vars = io.load_inference_model(
            str(tmp_path), exe2)
        assert feed_names == ["x"]
        (got,) = exe2.run(prog, feed={"x": xv}, fetch_list=fetch_vars)
        np.testing.assert_allclose(got, expected, rtol=1e-5)
        # label/loss ops pruned from the exported program
        types = [op.type for op in prog.global_block().ops]
        assert "cross_entropy" not in types and "sgd" not in types


def test_version_check_rejects_future(tmp_path):
    from paddle_tpu.core.desc import load_program_dict

    with pytest.raises(RuntimeError):
        load_program_dict('{"version": 99}')


def test_reader_decorators():
    def r():
        yield from range(10)

    assert list(firstn(r, 3)()) == [0, 1, 2]
    assert list(batch(r, 4)()) == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
    assert list(batch(r, 4, drop_last=True)()) == [[0, 1, 2, 3],
                                                   [4, 5, 6, 7]]
    assert sorted(shuffle(r, 5)()) == list(range(10))
    assert list(chain(r, r)()) == list(range(10)) * 2
    assert list(map_readers(lambda a, b: a + b, r, r)()) == \
        [2 * i for i in range(10)]
    assert list(compose(r, r)()) == [(i, i) for i in range(10)]
    assert sorted(buffered(r, 2)()) == list(range(10))
    got = sorted(xmap_readers(lambda s: s * 2, r, 3, 4)())
    assert got == [2 * i for i in range(10)]
    ordered = list(xmap_readers(lambda s: s * 2, r, 3, 4, order=True)())
    assert ordered == [2 * i for i in range(10)]


def test_data_feeder_pads_sequences():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = layers.data(name="w", shape=[-1], dtype="int64",
                            lod_level=1, append_batch_size=False)
        label = layers.data(name="l", shape=[1], dtype="int64",
                            append_batch_size=True)
        feeder = DataFeeder(feed_list=[words, label], program=main)
    batch_rows = [([1, 2, 3], 0), ([4, 5], 1), ([6], 0)]
    feed = feeder.feed(batch_rows)
    assert feed["w"].shape[0] == 3
    assert feed["w"].shape[1] % 8 == 0  # bucketed padding
    np.testing.assert_array_equal(feed["w.seq_len"], [3, 2, 1])
    np.testing.assert_array_equal(feed["w"][1, :2], [4, 5])
    assert feed["w"][1, 2] == 0
    assert feed["l"].shape == (3, 1)


def test_synthetic_datasets_contract():
    x, y = next(dataset.mnist.train(n=5)())
    assert x.shape == (1, 28, 28) and 0 <= y < 10
    x, y = next(dataset.uci_housing.train(n=5)())
    assert x.shape == (13,) and y.shape == (1,)
    toks, lbl = next(dataset.imdb.train(n=5)())
    assert toks.dtype == np.int64 and lbl in (0, 1)


def test_train_with_feeder_and_reader_pipeline():
    """End-to-end: dataset → shuffle/batch reader → DataFeeder →
    Executor (the reference's canonical training loop shape)."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        img = layers.data(name="img", shape=[1, 28, 28], dtype="float32")
        lbl = layers.data(name="lbl", shape=[1], dtype="int64")
        h = layers.fc(img, size=32, act="relu")
        logits = layers.fc(h, size=10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        feeder = DataFeeder(feed_list=[img, lbl], program=main)
        reader = batch(shuffle(dataset.mnist.train(n=256), 64), 32,
                       drop_last=True)
        losses = []
        for b in reader():
            rows = [(x, np.asarray([y], np.int64)) for x, y in b]
            (lv,) = exe.run(main, feed=feeder.feed(rows),
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert np.isfinite(losses).all() if hasattr(np, 'isfinite') else True
        assert losses[-1] < losses[0] * 2


def test_lod_level2_feed_and_pool():
    """Nested sequences (reference LoD level 2, lod_tensor.h:58): feed a
    batch of paragraphs (lists of sentences of word vectors), pool the
    innermost level, then the outer level."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers

    B, S1, S2, D = 2, 4, 8, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[B, S1, S2, D],
                        append_batch_size=False, lod_level=2)
        inner = layers.sequence_pool(x, "sum")       # (B, S1, D), lvl-1
        outer = layers.sequence_pool(inner, "sum")   # (B, D)
        feeder = fluid.DataFeeder(feed_list=[x], program=main)

    # sample 0: 2 sentences (3 and 1 words); sample 1: 1 sentence (2)
    rng = np.random.RandomState(0)
    s0 = [rng.rand(3, D).astype(np.float32),
          rng.rand(1, D).astype(np.float32)]
    s1v = [rng.rand(2, D).astype(np.float32)]
    feed = feeder.feed([(s0,), (s1v,)])
    assert feed["x"].shape == (2, S1, S2, D)
    np.testing.assert_array_equal(feed["x.seq_len"], [2, 1])
    assert feed["x.seq_len2"].shape == (2, S1)
    np.testing.assert_array_equal(feed["x.seq_len2"][0, :2], [3, 1])

    exe = fluid.Executor()
    (o,) = exe.run(main, feed=feed, fetch_list=[outer])
    want0 = s0[0].sum(axis=0) + s0[1].sum(axis=0)
    want1 = s1v[0].sum(axis=0)
    np.testing.assert_allclose(o[0], want0, rtol=1e-5)
    np.testing.assert_allclose(o[1], want1, rtol=1e-5)


def test_lod_level3_rejected():
    import pytest

    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        with pytest.raises(NotImplementedError):
            layers.data("deep", shape=[2, 3, 4, 5],
                        append_batch_size=False, lod_level=3)
