"""AsyncExecutor (multi-thread file-shard training) + contrib
Trainer/Inferencer (checkpoint recovery) tests.

reference patterns: python/paddle/fluid/tests/demo/async_executor.py,
contrib trainer usage in tests/book."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.contrib import CheckpointConfig, Inferencer, Trainer
from paddle_tpu.data.data_feed import DataFeedDesc, MultiSlotDataFeed


# ---------------------------------------------------------------------------
# DataFeed
# ---------------------------------------------------------------------------

def _write_multislot(path, rng, n_lines, vocab=50):
    """slots: sparse ids (var len <=5), dense 3-float, label."""
    with open(path, "w") as f:
        for _ in range(n_lines):
            k = rng.randint(1, 6)
            ids = rng.randint(0, vocab, k)
            dense = rng.rand(3)
            label = rng.randint(0, 2)
            parts = ([str(k)] + [str(i) for i in ids]
                     + ["3"] + [f"{v:.4f}" for v in dense]
                     + ["1", str(label)])
            f.write(" ".join(parts) + "\n")


def _desc(batch_size):
    return DataFeedDesc.from_slots([
        {"name": "ids", "type": "uint64", "dense": False, "max_len": 5},
        {"name": "dense", "type": "float", "dense": True, "dim": 3},
        {"name": "label", "type": "uint64", "dense": True, "dim": 1},
    ], batch_size=batch_size)


def test_multislot_datafeed_parses(tmp_path):
    rng = np.random.RandomState(0)
    p = os.path.join(tmp_path, "part-0")
    _write_multislot(p, rng, 10)
    feed = MultiSlotDataFeed(_desc(4))
    batches = list(feed.batches([p]))
    assert len(batches) == 2  # 10 lines, bs 4, trailing 2 dropped
    b = batches[0]
    assert b["ids"].shape == (4, 5)
    assert b["ids.seq_len"].shape == (4,)
    assert b["dense"].shape == (4, 3)
    assert b["label"].shape == (4, 1)
    assert (b["ids.seq_len"] >= 1).all()


def test_async_executor_trains_over_shards(tmp_path):
    rng = np.random.RandomState(1)
    files = []
    for i in range(4):
        p = os.path.join(tmp_path, f"part-{i}")
        _write_multislot(p, rng, 24)
        files.append(p)

    B = 8
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[B, 5], dtype="int64",
                          append_batch_size=False, lod_level=1)
        dense = layers.data("dense", shape=[B, 3],
                            append_batch_size=False)
        label = layers.data("label", shape=[B, 1], dtype="int64",
                            append_batch_size=False)
        emb = layers.embedding(ids, size=[50, 8], is_sparse=True)
        pooled = layers.sequence_pool(emb, "sum")
        feat = layers.concat([pooled, dense], axis=1)
        pred = layers.fc(feat, size=2)
        loss = layers.reduce_mean(layers.softmax_with_cross_entropy(
            pred, label))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
    aexe = fluid.AsyncExecutor()
    stats = aexe.run(main, _desc(B), files, thread_num=2,
                     fetch=[loss], scope=scope)
    assert np.isfinite(stats[loss.name])
    # 4 files × 24 lines / bs 8 = 12 batches; run again, loss lower
    stats2 = aexe.run(main, _desc(B), files, thread_num=2,
                      fetch=[loss], scope=scope)
    assert stats2[loss.name] < stats[loss.name]


def test_recordio_mnist_end_to_end(tmp_path):
    """VERDICT r3 item 5: real-data-shaped ingestion — a deterministic
    MNIST-scale dataset written to RecordIO shard files on disk, trained
    through the REAL file path: RecordIO codec → shard lease queue →
    MultiSlotDataFeed parser threads → DeviceFeeder → jitted train step,
    to a convergence threshold (reference analog:
    python/paddle/dataset/mnist.py feeding the book demos)."""
    from paddle_tpu.data import recordio

    rng = np.random.RandomState(42)
    n_cls, dim = 10, 64
    protos = rng.rand(n_cls, dim).astype(np.float32)

    def make_line(cls):
        x = protos[cls] + 0.25 * rng.randn(dim)
        return " ".join([str(dim)] + [f"{v:.4f}" for v in x]
                        + ["1", str(cls)])

    files = []
    for i in range(6):
        p = os.path.join(tmp_path, f"mnist-{i:05d}.recordio")
        with recordio.Writer(p, max_chunk_records=32) as w:
            for _ in range(160):
                w.write(make_line(rng.randint(0, n_cls)).encode())
        files.append(p)

    B = 32
    desc = DataFeedDesc.from_slots([
        {"name": "pixels", "type": "float", "dense": True, "dim": dim},
        {"name": "label", "type": "uint64", "dense": True, "dim": 1},
    ], batch_size=B)

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        pixels = layers.data("pixels", shape=[B, dim],
                             append_batch_size=False)
        label = layers.data("label", shape=[B, 1], dtype="int64",
                            append_batch_size=False)
        hidden = layers.fc(pixels, size=32, act="relu")
        pred = layers.fc(hidden, size=n_cls)
        loss = layers.reduce_mean(layers.softmax_with_cross_entropy(
            pred, label))
        acc = layers.accuracy(layers.softmax(pred), label)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    with fluid.scope_guard(scope):
        fluid.Executor().run(startup)

    aexe = fluid.AsyncExecutor()
    first = aexe.run(main, desc, files, thread_num=3,
                     fetch=[loss, acc], scope=scope)
    for _ in range(3):  # more epochs over the same shards
        stats = aexe.run(main, desc, files, thread_num=3,
                         fetch=[loss, acc], scope=scope)
    assert stats[loss.name] < first[loss.name]
    assert stats[acc.name] > 0.9, (
        f"RecordIO e2e did not converge: acc={stats[acc.name]:.3f}")


def test_async_executor_validates(tmp_path):
    main = fluid.Program()
    aexe = fluid.AsyncExecutor()
    with pytest.raises(ValueError):
        aexe.run(main, _desc(4), [], thread_num=2, fetch=[])
    with pytest.raises(ValueError):
        aexe.run(main, _desc(4), ["x"], thread_num=0, fetch=[])


def test_async_executor_surfaces_shard_errors(tmp_path):
    rng = np.random.RandomState(5)
    good = os.path.join(tmp_path, "part-0")
    _write_multislot(good, rng, 16)
    missing = os.path.join(tmp_path, "does-not-exist")
    B = 8
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        dense = layers.data("dense", shape=[B, 3],
                            append_batch_size=False)
        loss = layers.reduce_mean(dense)
    with fluid.scope_guard(scope):
        fluid.Executor().run(startup)
    desc = DataFeedDesc.from_slots(
        [{"name": "ids", "dense": False, "max_len": 5, "used": False},
         {"name": "dense", "type": "float", "dense": True, "dim": 3},
         {"name": "label", "dense": True, "dim": 1, "used": False}],
        batch_size=B)
    aexe = fluid.AsyncExecutor()
    with pytest.raises(RuntimeError, match="shard reader failed"):
        aexe.run(main, desc, [good, missing], thread_num=2,
                 fetch=[loss], scope=scope)


def test_multislot_uint64_hash_ids(tmp_path):
    p = os.path.join(tmp_path, "part-u")
    with open(p, "w") as f:
        f.write("2 9223372036854775808 3 1 0.5 1 1\n")
    desc = DataFeedDesc.from_slots(
        [{"name": "ids", "dense": False, "max_len": 4},
         {"name": "d", "type": "float", "dense": True, "dim": 1},
         {"name": "label", "dense": True, "dim": 1}], batch_size=1)
    (b,) = list(MultiSlotDataFeed(desc).batches([p]))
    # 2**63 reinterpreted into int64 (bit pattern preserved)
    assert b["ids"][0, 0] == np.uint64(2 ** 63).astype(np.int64)
    assert b["ids"][0, 1] == 3


def test_multislot_sparse_requires_max_len(tmp_path):
    p = os.path.join(tmp_path, "part-m")
    with open(p, "w") as f:
        f.write("1 7 1 1\n")
    desc = DataFeedDesc.from_slots(
        [{"name": "ids", "dense": False},
         {"name": "label", "dense": True, "dim": 1}], batch_size=1)
    with pytest.raises(ValueError, match="max_len"):
        list(MultiSlotDataFeed(desc).batches([p]))


# ---------------------------------------------------------------------------
# Trainer / Inferencer
# ---------------------------------------------------------------------------

def _make_reader(w, steps=8, B=4):
    def reader():
        rng = np.random.RandomState(3)
        for _ in range(steps):
            x = rng.rand(B, 4).astype(np.float32)
            yield {"x": x, "y": x @ w}
    return reader


def _train_func(B=4):
    x = layers.data("x", shape=[B, 4], append_batch_size=False)
    y = layers.data("y", shape=[B, 1], append_batch_size=False)
    pred = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="tw"),
                     bias_attr=False)
    return layers.reduce_mean(layers.square_error_cost(pred, y))


def test_trainer_without_checkpoint_config():
    w = np.random.RandomState(9).rand(4, 1).astype(np.float32)
    losses = []
    trainer = Trainer(
        train_func=_train_func,
        optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.2))
    trainer.train(
        num_epochs=1,
        event_handler=lambda e: losses.append(e.metrics[0])
        if type(e).__name__ == "EndStepEvent" else None,
        reader=_make_reader(w))
    assert len(losses) == 8
    assert float(losses[-1].reshape(-1)[0]) < float(
        losses[0].reshape(-1)[0])


def test_trainer_mid_epoch_resume_skips_consumed_batches(tmp_path):
    """A mid-epoch checkpoint resumes at the next batch of its epoch
    rather than replaying the epoch from batch 0."""
    w = np.random.RandomState(10).rand(4, 1).astype(np.float32)
    ckpt = os.path.join(tmp_path, "ck")
    # 8 steps/epoch, checkpoint every 3 steps: newest mid-epoch ckpt is
    # at step 6 of epoch 0 after we stop the first trainer "mid-crash"
    t1 = Trainer(
        train_func=_train_func,
        optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.2),
        checkpoint_config=CheckpointConfig(ckpt, max_num_checkpoints=1,
                                           step_interval=3,
                                           epoch_interval=10**9))
    seen = []

    class Stop(Exception):
        pass

    def crash_handler(e):
        if type(e).__name__ == "EndStepEvent":
            seen.append(e.step)
            if e.epoch == 0 and e.step == 6:
                raise Stop

    with pytest.raises(Stop):
        t1.train(num_epochs=1, event_handler=crash_handler,
                 reader=_make_reader(w))

    t2 = Trainer(
        train_func=_train_func,
        optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.2),
        checkpoint_config=CheckpointConfig(ckpt, max_num_checkpoints=1,
                                           step_interval=3,
                                           epoch_interval=10**9))
    assert t2._resume_epoch == 0
    assert t2._resume_step_in_epoch == 6
    resumed_steps = []
    t2.train(num_epochs=1,
             event_handler=lambda e: resumed_steps.append(e.step)
             if type(e).__name__ == "EndStepEvent" else None,
             reader=_make_reader(w))
    # only batches 6 and 7 of the epoch run after resume
    assert resumed_steps == [6, 7]


def test_trainer_events_checkpoint_resume(tmp_path):
    w = np.random.RandomState(2).rand(4, 1).astype(np.float32)
    ckpt = os.path.join(tmp_path, "ckpts")
    events = []

    trainer = Trainer(
        train_func=_train_func,
        optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.2),
        checkpoint_config=CheckpointConfig(ckpt, max_num_checkpoints=2,
                                           step_interval=4))
    trainer.train(num_epochs=2,
                  event_handler=lambda e: events.append(type(e).__name__),
                  reader=_make_reader(w))
    assert events.count("BeginEpochEvent") == 2
    assert events.count("EndStepEvent") == 16
    # checkpoints rotated to the cap
    names = [d for d in os.listdir(ckpt) if d.startswith("ckpt_")]
    assert 1 <= len(names) <= 2
    trained_w = np.asarray(trainer.scope.find_var("tw")).copy()

    # a fresh Trainer resumes from the newest checkpoint: same params,
    # and the finished epochs are not re-run
    steps_after_resume = []
    trainer2 = Trainer(
        train_func=_train_func,
        optimizer_func=lambda: fluid.optimizer.SGD(learning_rate=0.2),
        checkpoint_config=CheckpointConfig(ckpt, max_num_checkpoints=2,
                                           step_interval=4))
    resumed_w = np.asarray(trainer2.scope.find_var("tw"))
    np.testing.assert_allclose(resumed_w, trained_w)
    trainer2.train(num_epochs=2,
                   event_handler=lambda e: steps_after_resume.append(e),
                   reader=_make_reader(w))
    assert trainer2._resume_epoch == 2
    assert len([e for e in steps_after_resume
                if type(e).__name__ == "EndStepEvent"]) == 0

    # params export + Inferencer round-trip
    params_dir = os.path.join(tmp_path, "params")
    trainer.save_params(params_dir)

    def infer_func():
        x = layers.data("x", shape=[4, 4], append_batch_size=False)
        return layers.fc(x, size=1,
                         param_attr=fluid.ParamAttr(name="tw"),
                         bias_attr=False)

    inferencer = Inferencer(infer_func, params_dir)
    xv = np.random.RandomState(4).rand(4, 4).astype(np.float32)
    (pv,) = inferencer.infer({"x": xv})
    np.testing.assert_allclose(pv, xv @ trained_w, rtol=1e-5)


# -- round 3: shard-lease task queue (reference go/master/service.go) --------

def test_task_queue_lease_expiry_requeues():
    from paddle_tpu.data.task_queue import TaskQueue

    clock = [0.0]
    tq = TaskQueue(["a", "b"], lease_timeout=10.0, max_failures=3,
                   clock=lambda: clock[0])
    t1 = tq.acquire("w1")
    t2 = tq.acquire("w1")
    assert {t1.shard, t2.shard} == {"a", "b"}
    assert tq.acquire("w2") is None and not tq.all_done()
    tq.complete(t1.task_id, t1.lease)
    # w1 dies holding t2: after the lease expires another worker gets it
    clock[0] = 11.0
    t3 = tq.acquire("w2")
    assert t3 is not None and t3.shard == t2.shard
    assert t3.failures == 1
    tq.complete(t3.task_id, t3.lease)
    assert tq.all_done() and not tq.failed_tasks()


def test_task_queue_retires_after_max_failures():
    from paddle_tpu.data.task_queue import TaskQueue

    tq = TaskQueue(["x"], lease_timeout=100.0, max_failures=2)
    t = tq.acquire("w")
    assert tq.fail(t.task_id, t.lease)          # retry 1 allowed
    t = tq.acquire("w")
    assert not tq.fail(t.task_id, t.lease)      # retired
    assert tq.all_done()
    assert [d.shard for d in tq.failed_tasks()] == ["x"]


def test_task_queue_stale_lease_reports_are_ignored():
    """A worker whose lease expired must not complete/fail/renew the
    task out from under the new owner (service.go lease semantics)."""
    from paddle_tpu.data.task_queue import TaskQueue

    clock = [0.0]
    tq = TaskQueue(["x"], lease_timeout=10.0, max_failures=3,
                   clock=lambda: clock[0])
    t_old = tq.acquire("w1")
    clock[0] = 11.0                      # w1's lease expires
    t_new = tq.acquire("w2")
    assert t_new is not None and t_new.lease != t_old.lease
    # stale complete: must NOT retire w2's live lease
    tq.complete(t_old.task_id, t_old.lease)
    assert not tq.all_done()
    # stale fail: reported as "not your problem", no failure counted
    assert tq.fail(t_old.task_id, t_old.lease)
    assert tq.stats()["pending"] == 1
    assert not tq.renew(t_old.task_id, t_old.lease)
    assert tq.renew(t_new.task_id, t_new.lease)
    tq.complete(t_new.task_id, t_new.lease)
    assert tq.all_done() and not tq.failed_tasks()


def test_task_queue_renew_extends_lease():
    from paddle_tpu.data.task_queue import TaskQueue

    clock = [0.0]
    tq = TaskQueue(["x"], lease_timeout=10.0,
                   clock=lambda: clock[0])
    t = tq.acquire("w")
    clock[0] = 8.0
    assert tq.renew(t.task_id, t.lease)
    clock[0] = 16.0                      # past original deadline
    assert tq.acquire("w2") is None      # still leased (renewed)
    tq.complete(t.task_id, t.lease)
    assert tq.all_done()


def test_async_executor_does_not_hang_on_stalled_worker(tmp_path):
    """A parser thread stalled forever: its shard re-leases, the run
    completes, no deadlock waiting for the stalled thread's _STOP."""
    import threading

    rng = np.random.RandomState(8)
    files = []
    for i in range(3):
        p = os.path.join(tmp_path, f"part-{i}")
        _write_multislot(p, rng, 16)
        files.append(p)

    B = 8
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[B, 5], dtype="int64",
                          append_batch_size=False, lod_level=1)
        dense = layers.data("dense", shape=[B, 3],
                            append_batch_size=False)
        label = layers.data("label", shape=[B, 1], dtype="int64",
                            append_batch_size=False)
        emb = layers.embedding(ids, size=[50, 8], is_sparse=True)
        pooled = layers.sequence_pool(emb, "sum")
        feat = layers.concat([pooled, dense], axis=1)
        pred = layers.fc(feat, size=2)
        loss = layers.reduce_mean(layers.softmax_with_cross_entropy(
            pred, label))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)

    release = threading.Event()
    stalled = {"hit": False}
    orig = MultiSlotDataFeed.batches

    def stalling_batches(self, paths):
        # first thread to grab a shard stalls until the run finishes
        if not stalled["hit"]:
            stalled["hit"] = True
            release.wait(timeout=60)
        return orig(self, paths)

    aexe = fluid.AsyncExecutor()
    MultiSlotDataFeed.batches = stalling_batches
    try:
        stats = aexe.run(main, _desc(B), files, thread_num=2,
                         fetch=[loss], scope=scope,
                         shard_lease_timeout=1.0,
                         shard_max_failures=10)
    finally:
        release.set()
        MultiSlotDataFeed.batches = orig
    assert np.isfinite(stats[loss.name])
    assert stalled["hit"]


def test_async_executor_survives_worker_crash(tmp_path):
    """A shard whose parse fails transiently re-leases and retries; the
    run still covers every file (at-least-once re-delivery, the Go
    master's contract)."""
    import threading

    rng = np.random.RandomState(7)
    files = []
    for i in range(4):
        p = os.path.join(tmp_path, f"part-{i}")
        _write_multislot(p, rng, 16)
        files.append(p)

    B = 8
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[B, 5], dtype="int64",
                          append_batch_size=False, lod_level=1)
        dense = layers.data("dense", shape=[B, 3],
                            append_batch_size=False)
        label = layers.data("label", shape=[B, 1], dtype="int64",
                            append_batch_size=False)
        emb = layers.embedding(ids, size=[50, 8], is_sparse=True)
        pooled = layers.sequence_pool(emb, "sum")
        feat = layers.concat([pooled, dense], axis=1)
        pred = layers.fc(feat, size=2)
        loss = layers.reduce_mean(layers.softmax_with_cross_entropy(
            pred, label))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)

    flaky = {"left": 2}
    flaky_lock = threading.Lock()
    orig = MultiSlotDataFeed.batches

    def flaky_batches(self, paths):
        with flaky_lock:
            crash = flaky["left"] > 0
            if crash:
                flaky["left"] -= 1
        if crash:
            raise OSError(f"simulated shard read failure for {paths}")
        return orig(self, paths)

    aexe = fluid.AsyncExecutor()
    MultiSlotDataFeed.batches = flaky_batches
    try:
        stats = aexe.run(main, _desc(B), files, thread_num=2,
                         fetch=[loss], scope=scope,
                         shard_lease_timeout=30.0,
                         shard_max_failures=3)
    finally:
        MultiSlotDataFeed.batches = orig
    assert np.isfinite(stats[loss.name])
    assert flaky["left"] == 0  # the failures actually happened
