"""Cross-platform TPU lowering of the Pallas kernels.

The CPU test suite exercises these kernels through the Pallas
INTERPRETER, which proves numerics but not that the kernel IR lowers
for the real TPU target (r4 finding: interpreter != Mosaic).
jax.export with platforms=["tpu"] runs the actual Pallas->Mosaic
lowering rules on any host, so block-spec/primitive errors surface
here instead of on the first chip contact.  (The Mosaic->LLO compile
itself still happens on hardware — this pins everything before it.)
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import force_mosaic_lowering


def _export_fn():
    """Version-tolerant jax.export accessor: newer jax ships it as the
    `jax.export` SUBMODULE (not auto-imported — plain attribute access
    raises AttributeError), older jax as jax.experimental.export, and
    the keyword drifted lowering_platforms -> platforms along the way."""
    import inspect

    try:
        import jax.export as jexp  # jax >= 0.4.30
    except ImportError:
        from jax.experimental import export as jexp  # older jax
    sig = inspect.signature(jexp.export)
    kw = ("platforms" if "platforms" in sig.parameters
          else "lowering_platforms")

    def export(fn, *args):
        return jexp.export(jax.jit(fn), **{kw: ["tpu"]})(*args)

    return export


def _export_tpu(fn, *args):
    """Export for the TPU target with the interpret gate overridden —
    otherwise the CPU host would serialize the INTERPRETER path and
    the check would be vacuous."""

    with force_mosaic_lowering():
        exp = _export_fn()(fn, *args)
    # prove the Mosaic custom call is actually in the artifact
    mlir = exp.mlir_module()
    assert "tpu_custom_call" in mlir, \
        "export did not contain the Mosaic kernel (interpreter path?)"
    return exp


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(2, 4, 256, 64), jnp.float32)
    return mk(), mk(), mk()


def test_flash_attention_fwd_lowers_for_tpu(qkv):
    from paddle_tpu.ops.pallas.flash_attention import \
        pallas_flash_attention

    q, k, v = qkv
    exp = _export_tpu(
        lambda q, k, v: pallas_flash_attention(q, k, v, None, 0.125,
                                               True), q, k, v)
    assert len(exp.mlir_module_serialized) > 0
    assert "tpu" in exp.platforms


def test_flash_attention_bwd_lowers_for_tpu(qkv):
    from paddle_tpu.ops.pallas.flash_attention import \
        pallas_flash_attention

    q, k, v = qkv

    def loss(q, k, v):
        return jnp.sum(
            pallas_flash_attention(q, k, v, None, 0.125, True) ** 2)

    exp = _export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)
    assert len(exp.mlir_module_serialized) > 0


def test_vocab_ce_fwd_and_bwd_lower_for_tpu():
    from paddle_tpu.ops.pallas.vocab_ce import fused_vocab_ce

    rng = np.random.RandomState(1)
    h = jnp.asarray(rng.randn(8, 128, 256), jnp.float32)
    w = jnp.asarray(rng.randn(256, 4096) * 0.02, jnp.float32)
    lbl = jnp.asarray(rng.randint(0, 4096, (8, 128)), jnp.int32)

    def loss(h, w):
        return jnp.sum(fused_vocab_ce(h, w, lbl, 0.1, 1024, 2048))

    assert len(_export_tpu(loss, h, w).mlir_module_serialized) > 0
    assert len(_export_tpu(jax.grad(loss, argnums=(0, 1)), h,
                           w).mlir_module_serialized) > 0


def test_ring_attention_pallas_lowers_for_tpu():
    """Ring attention with the Pallas chunk kernel (SMEM offset
    scalars) inside shard_map over an sp mesh: fwd+bwd lower for the
    TPU target."""
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.ring_attention import ring_attention

    mesh = make_mesh({"sp": 8})
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(2, 2, 8 * 128, 64), jnp.float32)
    q, k, v = mk(), mk(), mk()

    def sp_loss(q, k, v):
        return jnp.mean(ring_attention(q, k, v, mesh, axis="sp",
                                       causal=True,
                                       use_pallas=True) ** 2)

    _export_tpu(jax.grad(sp_loss, argnums=(0, 1, 2)), q, k, v)


def test_full_longctx_train_step_lowers_for_tpu():
    """The COMPLETE fluid training step with every Pallas feature
    active — flash self+cross attention, fused vocab-CE, per-layer
    recompute, Adam — lowers for the TPU target (the longctx bench
    configuration's program shape)."""
    import paddle_tpu as fluid
    from paddle_tpu.core.executor import (RNG_STATE_VAR,
                                          interpret_program)
    from paddle_tpu.models import transformer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 0
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        model = transformer.build_model(
            src_vocab_size=512, trg_vocab_size=512, max_length=128,
            n_layer=2, n_head=2, d_model=128, d_inner_hid=256,
            dropout=0.1, with_optimizer=True, use_flash=True,
            use_fused_ce=True, flash_pallas=True, recompute=True,
            flash_cross=True)
        exe = fluid.Executor()
        exe.run(startup)

    loss_name = model["loss"].name
    state = {k: v for k, v in scope.vars.items() if v is not None}
    batch = transformer.make_fake_batch(2, max_length=128,
                                        src_vocab=512, trg_vocab=512)
    feeds = {k: jnp.asarray(v) for k, v in batch.items()}

    def step(st, feeds):
        rng = st[RNG_STATE_VAR]
        env = {k: v for k, v in st.items() if k != RNG_STATE_VAR}
        env.update(feeds)
        env = interpret_program(main, env, rng,
                                fetch_names=(loss_name,))
        return env[loss_name]

    exp = _export_tpu(step, state, feeds)
    # flash fwd+bwd (self + cross, enc + dec) and vocab-CE fwd+bwd all
    # reach Mosaic
    assert exp.mlir_module().count("tpu_custom_call") >= 5


def test_paged_attention_lowers_for_tpu():
    """The ragged paged-attention decode kernel (ISSUE 12) lowers to
    Mosaic for the TPU target — scalar-prefetched page-table block
    index maps included — and its module carries ZERO
    stablehlo.transpose (the head-major from-birth boundary proof,
    chip-free)."""
    from paddle_tpu.ops.pallas.paged_attention import \
        ragged_paged_attention

    s, h, d, p, page, maxp = 8, 4, 64, 32, 16, 8
    q = jnp.zeros((s, h * d), jnp.float32)
    kc = jnp.zeros((p, page, h * d), jnp.bfloat16)
    pt = jnp.zeros((s, maxp), jnp.int32)
    ln = jnp.ones((s,), jnp.int32)
    exp = _export_tpu(
        lambda q, kc, vc, pt, ln: ragged_paged_attention(
            q, kc, vc, pt, ln, n_head=h), q, kc, kc, pt, ln)
    mlir = exp.mlir_module()
    assert "stablehlo.transpose" not in mlir, \
        "transpose at the paged-attention kernel boundary"


def test_paged_attention_int8_lowers_for_tpu():
    """The int8-pool variant (per-row scale sidecars) also lowers."""
    from paddle_tpu.ops.pallas.paged_attention import \
        ragged_paged_attention

    s, h, d, p, page, maxp = 4, 2, 64, 16, 16, 4
    q = jnp.zeros((s, h * d), jnp.float32)
    kc = jnp.zeros((p, page, h * d), jnp.int8)
    sc = jnp.ones((p, page, 1), jnp.float32)
    pt = jnp.zeros((s, maxp), jnp.int32)
    ln = jnp.ones((s,), jnp.int32)
    exp = _export_tpu(
        lambda q, kc, vc, ks, vs, pt, ln: ragged_paged_attention(
            q, kc, vc, pt, ln, n_head=h, k_scales=ks, v_scales=vs),
        q, kc, kc, sc, sc, pt, ln)
    assert "stablehlo.transpose" not in exp.mlir_module()


def test_fused_lstm_fwd_lowers_for_tpu():
    from paddle_tpu.ops.pallas.recurrence import fused_lstm

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 16, 4 * 128), jnp.float32)
    w = jnp.asarray(rng.randn(128, 4 * 128), jnp.float32)
    sl = jnp.asarray(np.full(8, 16, np.int32))
    exp = _export_tpu(
        lambda x, w, sl: fused_lstm(x, w, seq_len=sl)[0], x, w, sl)
    assert len(exp.mlir_module_serialized) > 0


def test_fused_lstm_bwd_lowers_for_tpu():
    from paddle_tpu.ops.pallas.recurrence import fused_lstm

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 16, 4 * 128), jnp.float32)
    w = jnp.asarray(rng.randn(128, 4 * 128), jnp.float32)

    def loss(x, w):
        hs, cs, hl, cl = fused_lstm(x, w, is_reverse=True)
        return hs.sum() + cs.sum()

    exp = _export_tpu(
        lambda x, w: jax.grad(loss, argnums=(0, 1))(x, w), x, w)
    # fwd kernel (residual recompute path) + bwd kernel both reach
    # Mosaic
    assert exp.mlir_module().count("tpu_custom_call") >= 2
