"""Executor: compile a Program to one XLA computation and run it.

TPU-native analog of the reference C++ Executor
(reference: paddle/fluid/framework/executor.cc — Run:299, Prepare:372, the
op-by-op hot loop at :448-455, program cache in python executor.py:222).
The key design change: instead of interpreting OpDescs one at a time on a
device stream, the whole program — forward ops, the autodiff boundary
(core/backward.py), and optimizer update ops — is traced ONCE into a single
`jax.jit` function of shape

    step(state: {persistable: Array}, feeds: {name: Array})
        -> (new_state, fetches)

with the state argument donated.  XLA then fuses/schedules everything; eager
per-op garbage collection (executor.cc:45-134) is unnecessary because XLA's
buffer liveness analysis subsumes it.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .desc import normalize_dtype
from .program import (GRAD_SUFFIX, Parameter, Program, Variable,
                      grad_var_name)
from .registry import OpContext, get_op_impl

RNG_STATE_VAR = "__rng_key__"


class Scope:
    """Name → value store for persistable state (reference: scope.h:48).

    Parent-chain lookup is kept for API parity; values are jax Arrays (on
    device) or numpy arrays.
    """

    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self.vars: Dict[str, Any] = {}
        self.kids: List["Scope"] = []

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self.kids.append(kid)
        return kid

    def var(self, name: str):
        """Find-or-create (reference scope.h:56 Var)."""
        if name not in self.vars:
            self.vars[name] = None
        return self.vars[name]

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        return None

    def set_var(self, name: str, value):
        self.vars[name] = value

    def has_var(self, name: str) -> bool:
        return self.find_var(name) is not None

    def local_var_names(self) -> List[str]:
        return list(self.vars)

    def drop_kids(self):
        self.kids = []


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


import contextlib


@contextlib.contextmanager
def scope_guard(scope: Scope):
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = old


# ---------------------------------------------------------------------------
# Program interpretation (used inside jit traces)
# ---------------------------------------------------------------------------

def run_ops(ops, env: Dict[str, Any], rng_key, start_index: int = 0,
            amp_lists=None, program=None):
    """Interpret a straight-line op list over `env` (name → traced array).

    This runs under jax tracing: each op impl emits jaxpr; nothing executes
    eagerly.  Equivalent of the executor hot loop (executor.cc:448) but as a
    trace, compiled once.  With `amp_lists` set (paddle_tpu/amp.py), the
    bf16 dtype policy is applied at each op boundary inside the trace.
    Macro (control-flow) ops receive the whole env + their OpDesc and lower
    sub-blocks to lax primitives (ops/control_flow.py).
    """
    from .registry import get_macro_op_impl, is_macro_op

    for i, op in enumerate(ops):
        desc = op.desc
        try:
            if is_macro_op(desc.type):
                ctx = OpContext(rng_key, op_index=start_index + i,
                                program=program, amp_lists=amp_lists)
                get_macro_op_impl(desc.type)(ctx, env, desc)
                continue
            impl = get_op_impl(desc.type)
            ins = {
                slot: [env[n] for n in names]
                for slot, names in desc.inputs.items()
            }
            if amp_lists is not None:
                from ..amp import cast_ins_for_op

                ins = cast_ins_for_op(desc.type, ins, amp_lists)
            ctx = OpContext(rng_key, op_index=start_index + i,
                            program=program, amp_lists=amp_lists)
            outs = impl(ctx, ins, desc.attrs)
        except Exception as exc:
            _reraise_with_op_context(exc, desc, start_index + i)
        for slot, names in desc.outputs.items():
            values = outs.get(slot, [])
            if len(values) != len(names):
                raise RuntimeError(
                    f"op {desc.type}: output slot {slot!r} produced "
                    f"{len(values)} values for {len(names)} names"
                )
            for name, val in zip(names, values):
                env[name] = val
    return env


def _reraise_with_op_context(exc: Exception, desc, op_index: int):
    """Attach op type/index/io context to trace-time failures — the
    reference's PADDLE_ENFORCE discipline (platform/enforce.h) so a failing
    op inside a 500-op program is locatable.  The original traceback is
    preserved via exception chaining."""
    if isinstance(exc, (KeyboardInterrupt, SystemExit)):
        raise exc
    detail = (
        f"error while tracing op[{op_index}] {desc.type!r} "
        f"(inputs={desc.inputs}, outputs={desc.outputs}, "
        f"attrs={ {k: v for k, v in desc.attrs.items() if not str(k).startswith('_')} })"
    )
    try:
        new_exc = type(exc)(f"{detail}\n  caused by: {exc}")
    except Exception:
        new_exc = RuntimeError(f"{detail}\n  caused by: {exc!r}")
    raise new_exc from exc


def prune_ops(program: Program, fetch_names):
    """Dead-op elimination: keep ops contributing to fetches or writing
    persistable state (reference analog: Program pruning in
    framework/prune.cc + io.py save_inference_model's prune to targets).
    Training programs (with a backward boundary) are never pruned."""
    ops = program.global_block().ops
    if program._backward_info is not None:
        return ops
    block = program.global_block()

    def is_persistable(name: str) -> bool:
        return block.has_var(name) and block.var(name).persistable

    needed = set(fetch_names)
    keep = [False] * len(ops)
    for i in range(len(ops) - 1, -1, -1):
        desc = ops[i].desc
        outs = desc.output_names()
        if any(n in needed for n in outs) or any(
                is_persistable(n) for n in outs):
            keep[i] = True
            needed.update(desc.input_names())
    return [op for i, op in enumerate(ops) if keep[i]]


def _split_params(program: Program, env: Dict[str, Any]):
    info = program._backward_info
    trainable = {}
    for pname in info["params"]:
        if pname in env:
            trainable[pname] = env[pname]
    return trainable


def interpret_program(program: Program, env: Dict[str, Any], rng_key,
                      fetch_names=()):
    """Run the full program (forward [+ backward + update ops]) over env."""
    import jax

    info = program._backward_info
    amp_lists = getattr(program, "_amp_lists", None)
    if info is None:
        return run_ops(prune_ops(program, fetch_names), env, rng_key,
                       amp_lists=amp_lists, program=program)
    ops = program.global_block().ops

    k = info["index"]
    loss_name = info["loss"]
    fwd_ops, rest_ops = ops[:k], ops[k:]
    trainable = _split_params(program, env)

    def fwd(params, base_env):
        e = dict(base_env)
        e.update(params)
        run_ops(fwd_ops, e, rng_key, amp_lists=amp_lists, program=program)
        loss = e[loss_name]
        if loss.ndim > 0:
            import jax.numpy as jnp

            loss = jnp.squeeze(loss)
        return loss, e

    (loss_val, env_after), grads = jax.value_and_grad(fwd, has_aux=True)(
        trainable, env
    )
    env = env_after
    env[grad_var_name(loss_name)] = loss_val * 0 + 1.0
    for pname, g in grads.items():
        env[grad_var_name(pname)] = g
    # rest_ops[0] is the `backward_marker` op itself; skip it.
    run_ops(rest_ops[1:], env, rng_key, start_index=k + 1,
            amp_lists=amp_lists, program=program)
    return env


def _debug_checks(fetch_names, fetches, new_state):
    """FLAGS.check_nan_inf: the reference's post-op NaN scan
    (operator.cc:943 under FLAGS_check_nan_inf), applied per run to
    fetches and updated state; FLAGS.benchmark forces a blocking sync
    (operator.cc:940)."""
    from ..flags import FLAGS

    if FLAGS.check_nan_inf:
        for n, f in zip(fetch_names, fetches):
            arr = np.asarray(f)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                raise FloatingPointError(
                    f"NaN/Inf detected in fetched var {n!r}")
        for n, v in new_state.items():
            arr = np.asarray(v)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                raise FloatingPointError(
                    f"NaN/Inf detected in persistable var {n!r}")
    elif FLAGS.benchmark:
        for f in fetches:
            getattr(f, "block_until_ready", lambda: None)()


def chain_iterations(base_step, iterations: int):
    """Iteration batching: chain K executions of the program over the
    SAME feeds in one compiled call, amortizing host dispatch.  Note the
    feeds are frozen for all K iterations — this accelerates fixed-input
    loops (synthetic-data benchmarks, lr-search sweeps, steady-state
    profiling), NOT epoch training; feeding fresh batches still requires
    one run() per batch (device-side input pipelines come with the data
    plane).  Valid because state shapes are step-invariant."""
    if iterations <= 1:
        return base_step
    import jax

    def step(state, feeds):
        st, fetches = base_step(state, feeds)

        def body(_, carry):
            st, _f = carry
            return base_step(st, feeds)

        return jax.lax.fori_loop(1, iterations, body, (st, fetches))

    return step


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------

class Executor:
    """Compile-and-run engine (reference: python/paddle/fluid/executor.py:445
    Executor.run and paddle/fluid/framework/executor.cc).

    place is accepted for API parity; JAX device placement is controlled by
    the platform (real TPU) or by CompiledProgram shardings (parallel/).
    """

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Any, Any] = {}

    # -- public API ------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence[Any]] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True,
            use_program_cache: bool = True,
            iterations: int = 1):
        from .program import default_main_program

        import jax
        import jax.numpy as jnp

        program = program or default_main_program()
        scope = scope or global_scope()
        feed = dict(feed or {})
        fetch_names = [
            f.name if isinstance(f, Variable) else str(f)
            for f in (fetch_list or [])
        ]

        # `program` may be a CompiledProgram (passed directly, fluid style)
        # or a Program that was wrapped by CompiledProgram.
        if hasattr(program, "_program") and hasattr(program, "run"):
            return program.run(self, feed, fetch_names, scope,
                               return_numpy=return_numpy,
                               iterations=iterations)
        compiled = getattr(program, "_compiled_wrapper", None)
        if compiled is not None:
            return compiled.run(self, feed, fetch_names, scope,
                                return_numpy=return_numpy,
                                iterations=iterations)

        fn, state, feed_arrays = self._prepare(
            program, feed, fetch_names, scope, iterations,
            use_program_cache)
        new_state, fetches = fn(state, feed_arrays)
        for name, val in new_state.items():
            scope.set_var(name, val)
        _debug_checks(fetch_names, fetches, new_state)
        if return_numpy:
            fetches = [np.asarray(f) for f in fetches]
        return fetches

    def close(self):
        self._cache.clear()

    def cost_analysis(self, program: Program, feed=None, fetch_list=None,
                      scope: Optional[Scope] = None):
        """XLA cost analysis of the compiled one-iteration step (flops,
        bytes accessed).  TPU analog of the reference profiler's per-op
        accounting — here the unit is the whole fused step.  Returns the
        backend's dict (keys like 'flops', 'bytes accessed').  Note: the
        analysis needs an AOT `.lower().compile()`, one extra XLA compile
        beyond run()'s own jit cache (the jit-internal executable is not
        introspectable); the traced step fn itself is shared via the
        program cache."""
        feed = dict(feed or {})
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in (fetch_list or [])]
        fn, state, feed_arrays = self._prepare(
            program, feed, fetch_names, scope or global_scope(), 1, True)
        compiled = fn.lower(state, feed_arrays).compile()
        analyses = compiled.cost_analysis()
        # PJRT returns one dict (or a list with one per executable)
        if isinstance(analyses, (list, tuple)):
            analyses = analyses[0]
        return dict(analyses)

    def _prepare(self, program: Program, feed, fetch_names, scope,
                 iterations: int, use_program_cache: bool):
        """Shared run()/cost_analysis() setup: RNG init, state gathering,
        program-cache lookup, feed conversion."""
        import jax

        block = program.global_block()
        # Ensure RNG state exists whenever any op may need randomness.
        if RNG_STATE_VAR not in scope.vars:
            scope.set_var(RNG_STATE_VAR,
                          jax.random.PRNGKey(program.random_seed))
        state_names = tuple(sorted(
            v.name for v in block.vars.values()
            if v.persistable and scope.has_var(v.name)
        ))
        key = (program._uid, program._version, tuple(sorted(feed)),
               tuple(fetch_names), state_names, iterations)
        fn = self._cache.get(key) if use_program_cache else None
        if fn is None:
            fn = self._build_step_fn(program, tuple(sorted(feed)),
                                     tuple(fetch_names), state_names,
                                     iterations)
            if use_program_cache:
                self._cache[key] = fn
        state = {n: scope.find_var(n) for n in state_names}
        state[RNG_STATE_VAR] = scope.find_var(RNG_STATE_VAR)
        feed_arrays = {n: _to_array(v, block) for n, v in feed.items()}
        return fn, state, feed_arrays

    # -- compilation -----------------------------------------------------
    def _build_step_fn(self, program: Program, feed_names, fetch_names,
                       state_names, iterations: int = 1):
        import jax

        persistable_names = tuple(sorted(
            v.name for v in program.global_block().vars.values()
            if v.persistable
        ))

        def step(state, feeds):
            rng_key = state[RNG_STATE_VAR]
            env: Dict[str, Any] = {}
            env.update({k: v for k, v in state.items()
                        if k != RNG_STATE_VAR})
            env.update(feeds)
            env = interpret_program(program, env, rng_key,
                                    fetch_names=fetch_names)
            new_state = {
                n: env[n] for n in persistable_names if n in env
            }
            new_state[RNG_STATE_VAR] = jax.random.split(rng_key, 1)[0]
            fetches = [env[n] for n in fetch_names]
            return new_state, fetches

        return jax.jit(chain_iterations(step, iterations),
                       donate_argnums=(0,))


def _to_array(value, block):
    import jax.numpy as jnp

    if isinstance(value, np.ndarray):
        return jnp.asarray(value)
    if isinstance(value, (int, float, list, tuple)):
        return jnp.asarray(value)
    return value  # already a jax Array
