"""Built-in datasets.

reference: python/paddle/dataset/ — mnist, cifar, uci_housing, imdb,
imikolov, movielens, wmt14/16 auto-download readers.  This environment
is zero-egress, so downloading is impossible; instead each dataset has
BOTH:

- a real-format file parser (`reader_creator` / `data_dir=` arg) that
  ingests the dataset's actual on-disk format — MNIST idx-ubyte .gz
  (dataset/mnist.py:43 reader_creator), CIFAR python-pickle tar
  (dataset/cifar.py reader_creator), UCI housing whitespace table with
  the reference's avg/min-max normalization (uci_housing.py:68
  load_data) — used whenever files are present (point `data_dir` or
  $PADDLE_DATASET_HOME at them), and
- a deterministic synthetic generator with the real shapes/dtypes/label
  spaces as the zero-egress fallback.

The reader contract is the reference's: zero-arg callable yielding
samples.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np


def _dataset_home(sub):
    home = os.environ.get("PADDLE_DATASET_HOME")
    return os.path.join(home, sub) if home else None


def _find_archive(data_dir, sub, names):
    """Probe `data_dir` (or $PADDLE_DATASET_HOME/sub) for the first
    existing archive filename in `names`; None when absent."""
    if data_dir is None:
        data_dir = _dataset_home(sub)
    if data_dir is None:
        return None
    for name in names:
        p = os.path.join(data_dir, name)
        if os.path.exists(p):
            return p
    return None


def _synthetic_classification(n, feature_shape, num_classes, seed,
                              flatten=False):
    rng = np.random.RandomState(seed)
    centers = rng.randn(num_classes, *feature_shape).astype(np.float32)

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(n):
            y = int(r.randint(num_classes))
            x = centers[y] + 0.5 * r.randn(*feature_shape).astype(np.float32)
            if flatten:
                x = x.reshape(-1)
            yield x, y

    return reader


class mnist:
    """28x28 grayscale digits, labels 0-9 (dataset/mnist.py)."""

    TRAIN_IMAGES = "train-images-idx3-ubyte.gz"
    TRAIN_LABELS = "train-labels-idx1-ubyte.gz"
    TEST_IMAGES = "t10k-images-idx3-ubyte.gz"
    TEST_LABELS = "t10k-labels-idx1-ubyte.gz"

    @staticmethod
    def reader_creator(image_filename, label_filename):
        """Parse the REAL idx-ubyte format (dataset/mnist.py:43): gzip'd
        big-endian headers (magic 2051 images / 2049 labels), raw u8
        pixels scaled to [-1, 1) exactly like the reference
        (`images / 255.0 * 2.0 - 1.0`); yields (flat f32 784, int)."""

        def reader():
            with gzip.open(image_filename, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                if magic != 2051:
                    raise IOError(
                        f"bad idx3 magic {magic} in {image_filename}")
                images = np.frombuffer(f.read(n * rows * cols),
                                       np.uint8).reshape(n, rows * cols)
            with gzip.open(label_filename, "rb") as f:
                magic, ln = struct.unpack(">II", f.read(8))
                if magic != 2049:
                    raise IOError(
                        f"bad idx1 magic {magic} in {label_filename}")
                labels = np.frombuffer(f.read(ln), np.uint8)
            if ln != n:
                raise IOError(f"mnist: {n} images but {ln} labels")
            imgs = images.astype(np.float32) / 255.0 * 2.0 - 1.0
            for i in range(n):
                yield imgs[i], int(labels[i])

        return reader

    @staticmethod
    def _files_in(data_dir, img, lbl):
        if data_dir is None:
            data_dir = _dataset_home("mnist")
        if data_dir is None:
            return None
        pi, pl = os.path.join(data_dir, img), os.path.join(data_dir, lbl)
        return (pi, pl) if (os.path.exists(pi)
                            and os.path.exists(pl)) else None

    @staticmethod
    def train(n=60000, seed=0, data_dir=None):
        real = mnist._files_in(data_dir, mnist.TRAIN_IMAGES,
                               mnist.TRAIN_LABELS)
        if real:
            return mnist.reader_creator(*real)
        return _synthetic_classification(n, (1, 28, 28), 10, seed)

    @staticmethod
    def test(n=10000, seed=7, data_dir=None):
        real = mnist._files_in(data_dir, mnist.TEST_IMAGES,
                               mnist.TEST_LABELS)
        if real:
            return mnist.reader_creator(*real)
        return _synthetic_classification(n, (1, 28, 28), 10, seed)


class cifar:
    @staticmethod
    def reader_creator(filename, sub_name):
        """Parse the REAL python-pickle tar format (dataset/cifar.py
        reader_creator): members whose name contains `sub_name` hold
        dicts with b'data' (N, 3072 u8) and b'labels'/b'fine_labels';
        pixels scale to [0, 1] f32 like the reference."""

        def reader():
            with tarfile.open(filename, mode="r") as f:
                names = [m.name for m in f if sub_name in m.name]
                for name in sorted(names):
                    batch = pickle.load(f.extractfile(name),
                                        encoding="bytes")
                    data = batch[b"data"]
                    labels = batch.get(b"labels",
                                       batch.get(b"fine_labels"))
                    if labels is None:
                        raise IOError(f"no labels in {name}")
                    for row, label in zip(data, labels):
                        yield ((np.asarray(row, np.uint8) / 255.0)
                               .astype(np.float32), int(label))

        return reader

    @staticmethod
    def _tar(data_dir, fname):
        return _find_archive(data_dir, "cifar", (fname,))

    @staticmethod
    def train10(n=50000, seed=1, data_dir=None):
        p = cifar._tar(data_dir, "cifar-10-python.tar.gz")
        if p:
            return cifar.reader_creator(p, "data_batch")
        return _synthetic_classification(n, (3, 32, 32), 10, seed)

    @staticmethod
    def test10(n=10000, seed=8, data_dir=None):
        p = cifar._tar(data_dir, "cifar-10-python.tar.gz")
        if p:
            return cifar.reader_creator(p, "test_batch")
        return _synthetic_classification(n, (3, 32, 32), 10, seed)

    @staticmethod
    def train100(n=50000, seed=2, data_dir=None):
        p = cifar._tar(data_dir, "cifar-100-python.tar.gz")
        if p:
            return cifar.reader_creator(p, "train")
        return _synthetic_classification(n, (3, 32, 32), 100, seed)


class flowers:
    """Oxford 102 Flowers (dataset/flowers.py): 102flowers.tgz of
    jpg_XXXXX.jpg images + imagelabels.mat (1-based labels) +
    setid.mat whose trnid/tstid/valid vectors hold 1-based image
    indices per split (flowers.py:110-115).  Yields (CHW float32
    in [0,1] resized 224x224, 0-based label)."""

    @staticmethod
    def _files(data_dir):
        if data_dir is None:
            data_dir = _dataset_home("flowers")
        if data_dir is None:
            return None
        paths = [os.path.join(data_dir, f) for f in
                 ("102flowers.tgz", "imagelabels.mat", "setid.mat")]
        return paths if all(os.path.exists(p) for p in paths) else None

    @staticmethod
    def reader_creator(tgz, label_mat, setid_mat, split,
                       is_train=False, seed=0):
        import io

        import scipy.io as scio

        # ONE augmentation stream across epochs: reseeding inside
        # reader() would give every epoch identical "random" crops
        rng = np.random.RandomState(seed)

        def reader():
            from PIL import Image

            from .image import simple_transform
            labels = scio.loadmat(label_mat)["labels"][0]
            idxs = scio.loadmat(setid_mat)[split][0]
            wanted = {"image_%05d.jpg" % i: int(i) for i in idxs}
            seen = 0
            # ONE forward pass over the gzip stream, yielding split
            # members in ARCHIVE order — random access on a 'r:gz' tar
            # re-inflates from byte 0 per backward seek (~N full
            # decompressions per epoch on the real 330 MB archive).
            # Order divergence vs the reference's index order is
            # documented; shuffle in the reader pipeline as usual.
            with tarfile.open(tgz) as t:
                for m in t:
                    i = wanted.get(os.path.basename(m.name))
                    if i is None:
                        continue
                    seen += 1
                    raw = t.extractfile(m).read()
                    im = np.asarray(
                        Image.open(io.BytesIO(raw)).convert("RGB"),
                        np.float32) / 255.0
                    # train: random crop + flip (the reference
                    # train_mapper); eval: center crop
                    im = simple_transform(im, 256, 224,
                                          is_train=is_train, rng=rng)
                    yield im.astype(np.float32), int(labels[i - 1]) - 1
            if seen != len(wanted):
                raise IOError(
                    f"flowers: {len(wanted) - seen} of {len(wanted)} "
                    f"{split} images missing from {tgz!r}")

        return reader

    @staticmethod
    def train(n=6149, seed=3, data_dir=None):
        real = flowers._files(data_dir)
        if real:
            return flowers.reader_creator(*real, split="trnid",
                                          is_train=True, seed=seed)
        return _synthetic_classification(n, (3, 224, 224), 102, seed)

    @staticmethod
    def test(n=1020, seed=9, data_dir=None):
        real = flowers._files(data_dir)
        if real:
            return flowers.reader_creator(*real, split="tstid")
        return _synthetic_classification(n, (3, 224, 224), 102, seed)

    @staticmethod
    def valid(n=1020, seed=10, data_dir=None):
        real = flowers._files(data_dir)
        if real:
            return flowers.reader_creator(*real, split="valid")
        return _synthetic_classification(n, (3, 224, 224), 102, seed)


class uci_housing:
    """13 features → scalar price (dataset/uci_housing.py)."""

    FEATURE_NUM = 14

    @staticmethod
    def load_data(filename, feature_num=14, ratio=0.8):
        """Parse the REAL whitespace table and normalize exactly like
        the reference (uci_housing.py:68): per-feature
        (x - avg) / (max - min) on the 13 inputs, 80/20 split."""
        data = np.fromfile(filename, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maxs, mins = data.max(axis=0), data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
        offset = int(data.shape[0] * ratio)
        return data[:offset], data[offset:]

    @staticmethod
    def _real_reader(data_dir, part):
        if data_dir is None:
            data_dir = _dataset_home("uci_housing")
        if data_dir is None:
            return None
        p = os.path.join(data_dir, "housing.data")
        if not os.path.exists(p):
            return None
        tr, te = uci_housing.load_data(p)
        rows = tr if part == "train" else te

        def reader():
            for row in rows:
                yield (row[:-1].astype(np.float32),
                       np.asarray([row[-1]], np.float32))

        return reader

    @staticmethod
    def train(n=404, seed=4, data_dir=None):
        real = uci_housing._real_reader(data_dir, "train")
        if real:
            return real
        rng = np.random.RandomState(seed)
        w = rng.randn(13).astype(np.float32)

        def reader():
            r = np.random.RandomState(seed + 1)
            for _ in range(n):
                x = r.randn(13).astype(np.float32)
                y = float(x @ w + 0.1 * r.randn())
                yield x, np.asarray([y], np.float32)

        return reader

    @staticmethod
    def test(n=404, seed=4, data_dir=None):
        real = uci_housing._real_reader(data_dir, "test")
        if real:
            return real
        # forward the SAME data_dir: a typo'd explicit dir must not
        # re-resolve the env home and hand back real train data
        return uci_housing.train(n, seed, data_dir=data_dir)


class imdb:
    """Variable-length token sequences, binary sentiment
    (dataset/imdb.py)."""

    word_dict_size = 5147
    TAR = "aclImdb_v1.tar.gz"

    # -- real-format path (dataset/imdb.py tokenize/build_dict/
    # reader_creator over the aclImdb tar: pos label 0, neg label 1) --
    @staticmethod
    def tokenize(tar_path, pattern):
        import re
        import string

        rx = re.compile(pattern)
        with tarfile.open(tar_path) as tarf:
            for tf in tarf:
                if rx.match(tf.name):
                    text = tarf.extractfile(tf).read().rstrip(b"\n\r")
                    text = text.translate(
                        None, string.punctuation.encode("latin-1"))
                    yield text.lower().split()

    # the reference's corpus pattern/cutoff (dataset/imdb.py word_dict):
    # labeled train+test docs only (unsup/ and urls_*.txt excluded),
    # words kept above 150 occurrences
    DICT_PATTERN = r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"

    @staticmethod
    def build_dict(tar_path, pattern=DICT_PATTERN, cutoff=150):
        freq: dict = {}
        for doc in imdb.tokenize(tar_path, pattern):
            for w in doc:
                freq[w] = freq.get(w, 0) + 1
        words = sorted((w for w, c in freq.items() if c > cutoff),
                       key=lambda w: (-freq[w], w))
        idx = {w: i for i, w in enumerate(words)}
        idx[b"<unk>"] = len(idx)
        return idx

    @staticmethod
    def reader_creator(tar_path, pos_pattern, neg_pattern, word_idx):
        unk = word_idx[b"<unk>"]

        def reader():
            for pattern, label in ((pos_pattern, 0), (neg_pattern, 1)):
                for doc in imdb.tokenize(tar_path, pattern):
                    yield [word_idx.get(w, unk) for w in doc], label

        return reader

    @staticmethod
    def _tar(data_dir):
        return _find_archive(data_dir, "imdb", (imdb.TAR,))

    @staticmethod
    def word_dict(data_dir=None):
        p = imdb._tar(data_dir)
        if p:
            return imdb.build_dict(p)
        return {i: i for i in range(imdb.word_dict_size)}

    @staticmethod
    def train(word_dict=None, n=25000, seed=5, max_len=200,
              data_dir=None):
        p = imdb._tar(data_dir)
        if p:
            if word_dict is None:
                word_dict = imdb.build_dict(p)
            return imdb.reader_creator(
                p, r"aclImdb/train/pos/.*\.txt$",
                r"aclImdb/train/neg/.*\.txt$", word_dict)
        vocab = imdb.word_dict_size

        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                length = int(r.randint(10, max_len))
                label = int(r.randint(2))
                # class-dependent token bias so models can actually learn
                lo = 0 if label == 0 else vocab // 2
                tokens = r.randint(lo, lo + vocab // 2,
                                   size=(length,)).astype(np.int64)
                yield tokens, label

        return reader

    @staticmethod
    def test(word_dict=None, n=25000, seed=11, max_len=200,
             data_dir=None):
        p = imdb._tar(data_dir)
        if p:
            if word_dict is None:
                word_dict = imdb.build_dict(p)
            return imdb.reader_creator(
                p, r"aclImdb/test/pos/.*\.txt$",
                r"aclImdb/test/neg/.*\.txt$", word_dict)
        # no real tar found for THIS data_dir: fall back to synthetic
        # without re-resolving the env home (a typo'd explicit dir must
        # not silently hand back real train data as the test set)
        return imdb.train(word_dict, n, seed, max_len,
                          data_dir=data_dir)


class imikolov:
    """PTB n-gram LM windows (dataset/imikolov.py): simple-examples.tgz
    holding ./simple-examples/data/ptb.{train,valid}.txt.  The dict is
    built from train+valid counts, words with freq > min_word_freq
    sorted by (-freq, word), '<unk>' appended LAST (imikolov.py:53-80);
    NGRAM mode yields n-windows over <s> line <e>, SEQ mode yields
    (<s>+ids, ids+<e>) pairs dropping lines longer than n
    (imikolov.py:83-109)."""

    NGRAM, SEQ = "NGRAM", "SEQ"
    TRAIN = "./simple-examples/data/ptb.train.txt"
    VALID = "./simple-examples/data/ptb.valid.txt"

    @staticmethod
    def _tar(data_dir):
        return _find_archive(
            data_dir, "imikolov",
            ("simple-examples.tgz", "simple-examples.tar.gz"))

    @staticmethod
    def _member(tf, name):
        # tar member names may or may not carry the leading "./"
        try:
            return tf.extractfile(name)
        except KeyError:
            return tf.extractfile(name[2:])

    @staticmethod
    def build_dict(min_word_freq=50, data_dir=None):
        tp = imikolov._tar(data_dir)
        if tp is None:
            # zero-egress fallback: fixed-size synthetic id space
            return {i: i for i in range(2073)}
        from collections import Counter

        freq = Counter()
        with tarfile.open(tp) as tf:
            for member in (imikolov.TRAIN, imikolov.VALID):
                for line in imikolov._member(tf, member):
                    words = line.decode("utf-8").strip().split()
                    freq.update(["<s>", "<e>"] + words)
        freq.pop("<unk>", None)
        kept = sorted(
            (x for x in freq.items() if x[1] > min_word_freq),
            key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _c) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    @staticmethod
    def reader_creator(tar_path, member, word_idx, n, data_type):
        def reader():
            unk = word_idx["<unk>"]
            with tarfile.open(tar_path) as tf:
                for line in imikolov._member(tf, member):
                    words = line.decode("utf-8").strip().split()
                    if data_type == imikolov.NGRAM:
                        l = ["<s>"] + words + ["<e>"]
                        if len(l) >= n:
                            ids = [word_idx.get(w, unk) for w in l]
                            for i in range(n, len(ids) + 1):
                                yield tuple(ids[i - n:i])
                    elif data_type == imikolov.SEQ:
                        ids = [word_idx.get(w, unk) for w in words]
                        src = [word_idx.get("<s>", unk)] + ids
                        if n > 0 and len(src) > n:
                            continue
                        yield src, ids + [word_idx.get("<e>", unk)]
                    else:
                        raise ValueError(
                            f"imikolov: unknown data_type {data_type!r}")

        return reader

    @staticmethod
    def _creator(member, word_dict, n, data_type, data_dir, samples,
                 seed):
        data_type = data_type or imikolov.NGRAM
        tp = imikolov._tar(data_dir)
        if tp is not None:
            wd = word_dict or imikolov.build_dict(data_dir=data_dir)
            return imikolov.reader_creator(tp, member, wd, n, data_type)
        vocab = len(word_dict) if word_dict else 2073

        def reader():
            # the zero-egress fallback must match the real path's
            # sample shape per data_type
            r = np.random.RandomState(seed)
            if data_type == imikolov.NGRAM:
                for _ in range(samples):
                    yield tuple(int(x)
                                for x in r.randint(0, vocab,
                                                   size=(max(n, 1),)))
            elif data_type == imikolov.SEQ:
                for _ in range(samples):
                    ln = int(r.randint(3, max(n, 4) if n > 0 else 12))
                    ids = [int(x) for x in r.randint(3, vocab, ln)]
                    yield [0] + ids, ids + [1]
            else:
                raise ValueError(
                    f"imikolov: unknown data_type {data_type!r}")

        return reader

    @staticmethod
    def train(word_dict=None, n=5, data_type=None, data_dir=None,
              seed=6, samples=100000):
        return imikolov._creator(imikolov.TRAIN, word_dict, n,
                                 data_type, data_dir, samples, seed)

    @staticmethod
    def test(word_dict=None, n=5, data_type=None, data_dir=None,
             seed=13, samples=10000):
        return imikolov._creator(imikolov.VALID, word_dict, n,
                                 data_type, data_dir, samples, seed)

class movielens:
    """MovieLens 1-M (dataset/movielens.py): `ml-1m.zip` holding
    movies.dat / users.dat / ratings.dat ('::'-separated, latin-1).
    Sample layout is the reference's `usr.value() + mov.value() +
    [[rating]]`:

        [user_id, gender(0=M,1=F), age_bucket_idx, job_id,
         movie_id, [category ids], [title word ids], [rating]]

    with rating scaled `* 2 - 5` (movielens.py:160) and the age mapped
    through `age_table` (movielens.py:41).  Divergence: the category /
    title-word vocabularies are SORTED for determinism (the reference
    enumerates python-set iteration order, movielens.py:132-139).
    data_dir may hold the zip or the extracted ml-1m/ files."""

    age_table = [1, 18, 25, 35, 45, 50, 56]

    @staticmethod
    def _read_members(data_dir):
        """→ {name: text lines} for movies/users/ratings, from
        ml-1m.zip or a plain directory (None when absent)."""
        import io
        import zipfile

        if data_dir is None:
            return None
        names = ("movies.dat", "users.dat", "ratings.dat")
        zp = os.path.join(data_dir, "ml-1m.zip")
        out = {}
        if os.path.exists(zp):
            with zipfile.ZipFile(zp) as z:
                for n in names:
                    with z.open(f"ml-1m/{n}") as f:
                        out[n] = io.TextIOWrapper(
                            io.BytesIO(f.read()),
                            encoding="latin-1").readlines()
            return out
        for n in names:
            p = os.path.join(data_dir, n)
            if not os.path.exists(p):
                p2 = os.path.join(data_dir, "ml-1m", n)
                p = p2 if os.path.exists(p2) else p
            if not os.path.exists(p):
                return None
            with open(p, encoding="latin-1") as f:
                out[n] = f.readlines()
        return out

    @staticmethod
    def load_meta(data_dir):
        """Parse movies.dat/users.dat → (movie_info, user_info,
        title_dict, categories_dict).  movie_info[id] = (id, [cat ids],
        [title word ids]); user_info[id] = (id, gender01, age_idx,
        job)."""
        import re

        members = movielens._read_members(data_dir)
        if members is None:
            raise IOError(
                f"movielens: no ml-1m.zip or *.dat under {data_dir!r} "
                f"(pass data_dir= or set $PADDLE_DATASET_HOME)")
        return movielens._parse_meta(members)

    @staticmethod
    def _parse_meta(members):
        import re
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        raw_movies = []
        title_words, categories = set(), set()
        for line in members["movies.dat"]:
            if not line.strip():
                continue
            mid, title, cats = line.strip().split("::")
            cats = cats.split("|")
            m = pattern.match(title)
            title = m.group(1) if m else title
            words = [w.lower() for w in title.split()]
            raw_movies.append((int(mid), cats, words))
            title_words.update(words)
            categories.update(cats)
        title_dict = {w: i for i, w in enumerate(sorted(title_words))}
        cat_dict = {c: i for i, c in enumerate(sorted(categories))}
        movie_info = {
            mid: (mid, [cat_dict[c] for c in cats],
                  [title_dict[w] for w in words])
            for mid, cats, words in raw_movies
        }
        user_info = {}
        for line in members["users.dat"]:
            if not line.strip():
                continue
            uid, gender, age, job = line.strip().split("::")[:4]
            user_info[int(uid)] = (
                int(uid), 0 if gender == "M" else 1,
                movielens.age_table.index(int(age)), int(job))
        return movie_info, user_info, title_dict, cat_dict

    @staticmethod
    def reader_creator(data_dir, is_test=False, test_ratio=0.1,
                       rand_seed=0):
        # parse the archive ONCE, lazily at first use, shared by every
        # epoch's reader() call (the real ml-1m is ~24 MB; re-parsing
        # per epoch would dominate data time)
        cache = []

        def reader():
            if not cache:
                members = movielens._read_members(data_dir)
                if members is None:
                    raise IOError(
                        f"movielens: no ml-1m.zip or *.dat under "
                        f"{data_dir!r}")
                movie_info, user_info, _, _ = \
                    movielens._parse_meta(members)
                cache.append((members["ratings.dat"], movie_info,
                              user_info))
            ratings, movie_info, user_info = cache[0]
            r = np.random.RandomState(rand_seed)
            for line in ratings:
                if not line.strip():
                    continue
                take = (r.random_sample() < test_ratio) == is_test
                if not take:
                    continue
                uid, mid, rating = line.strip().split("::")[:3]
                usr = user_info[int(uid)]
                mov = movie_info[int(mid)]
                yield (list(usr) + [mov[0], mov[1], mov[2]]
                       + [[float(rating) * 2 - 5.0]])

        return reader

    @staticmethod
    def _dir(data_dir):
        return data_dir or _dataset_home("movielens")

    @staticmethod
    def _present(data_dir):
        """Cheap existence probe (no archive read)."""
        if data_dir is None:
            return False
        if os.path.exists(os.path.join(data_dir, "ml-1m.zip")):
            return True
        return all(
            os.path.exists(os.path.join(data_dir, n))
            or os.path.exists(os.path.join(data_dir, "ml-1m", n))
            for n in ("movies.dat", "users.dat", "ratings.dat"))

    @staticmethod
    def _synthetic(n, seed, user_vocab=100, movie_vocab=200):
        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                uid = int(r.randint(1, user_vocab))
                mid = int(r.randint(1, movie_vocab))
                cats = [int(c) for c in r.randint(0, 18, r.randint(1, 4))]
                title = [int(t) for t in r.randint(0, 500,
                                                   r.randint(1, 8))]
                rating = float((uid + mid) % 5 + 1) * 2 - 5.0
                yield [uid, int(r.randint(0, 2)), int(r.randint(0, 7)),
                       int(r.randint(0, 21)), mid, cats, title,
                       [rating]]

        return reader

    @staticmethod
    def train(n=9000, seed=14, data_dir=None, test_ratio=0.1):
        d = movielens._dir(data_dir)
        if movielens._present(d):
            return movielens.reader_creator(d, is_test=False,
                                            test_ratio=test_ratio)
        return movielens._synthetic(n, seed)

    @staticmethod
    def test(n=1000, seed=15, data_dir=None, test_ratio=0.1):
        d = movielens._dir(data_dir)
        if movielens._present(d):
            return movielens.reader_creator(d, is_test=True,
                                            test_ratio=test_ratio)
        return movielens._synthetic(n, seed)

    @staticmethod
    def max_user_id(data_dir=None):
        _, u, _, _ = movielens.load_meta(movielens._dir(data_dir))
        return max(u)

    @staticmethod
    def max_movie_id(data_dir=None):
        m, _, _, _ = movielens.load_meta(movielens._dir(data_dir))
        return max(m)

    @staticmethod
    def max_job_id(data_dir=None):
        _, u, _, _ = movielens.load_meta(movielens._dir(data_dir))
        return max(v[3] for v in u.values())

    @staticmethod
    def get_movie_title_dict(data_dir=None):
        _, _, t, _ = movielens.load_meta(movielens._dir(data_dir))
        return t

    @staticmethod
    def movie_categories(data_dir=None):
        _, _, _, c = movielens.load_meta(movielens._dir(data_dir))
        return sorted(c)

    @staticmethod
    def batches_for_model(reader, batch_size, title_len=12):
        """Adapt raw movielens samples to models/recommender.py feeds:
        titles pad/truncate to `title_len` with a companion seq_len,
        category list is pooled away (the model's movie tower consumes
        id + title only, like the reference book test)."""

        def gen():
            buf = []
            for s in reader():
                buf.append(s)
                if len(buf) == batch_size:
                    yield movielens._to_feed(buf, title_len)
                    buf = []

        return gen

    @staticmethod
    def _to_feed(buf, title_len):
        b = len(buf)
        title = np.zeros((b, title_len), np.int64)
        tlen = np.zeros((b,), np.int32)
        for i, s in enumerate(buf):
            words = s[6][:title_len]
            title[i, :len(words)] = words
            tlen[i] = max(1, len(words))
        col = lambda j, dt: np.asarray([s[j] for s in buf],
                                       dt).reshape(b, 1)
        return {
            "user_id": col(0, np.int64),
            "gender_id": col(1, np.int64),
            "age_id": col(2, np.int64),
            "job_id": col(3, np.int64),
            "movie_id": col(4, np.int64),
            "title_ids": title,
            "title_ids.seq_len": tlen,
            "score": np.asarray([s[7][0] for s in buf],
                                np.float32).reshape(b, 1),
        }

class wmt14:
    """WMT14 en→fr subset (dataset/wmt14.py): a tar holding
    `*/src.dict`, `*/trg.dict` (one token per line, line number = id)
    and tab-separated parallel text under `train/train`, `test/test`.
    Sample = (src_ids with <s>/<e> framing, <s>+trg_ids,
    trg_ids+<e>); pairs with either side >80 tokens are dropped
    (wmt14.py:107) and OOV maps to UNK_IDX=2 (wmt14.py:53)."""

    START, END, UNK = "<s>", "<e>", "<unk>"
    UNK_IDX = 2

    @staticmethod
    def _tar(data_dir):
        return _find_archive(data_dir, "wmt14",
                             ("wmt14.tgz", "wmt14.tar.gz", "wmt14.tar"))

    @staticmethod
    def _dicts(tar_path, dict_size):
        def to_dict(fd, size):
            return {line.decode("utf-8").strip(): i
                    for i, line in enumerate(fd) if i < size}

        with tarfile.open(tar_path) as f:
            src = [m.name for m in f if m.name.endswith("src.dict")]
            trg = [m.name for m in f if m.name.endswith("trg.dict")]
            if len(src) != 1 or len(trg) != 1:
                raise IOError(
                    f"wmt14: expected exactly one src.dict and one "
                    f"trg.dict in {tar_path!r}")
            return (to_dict(f.extractfile(src[0]), dict_size),
                    to_dict(f.extractfile(trg[0]), dict_size))

    @staticmethod
    def reader_creator(tar_path, file_name, dict_size):
        cache = []  # dicts parsed once, shared by every epoch

        def reader():
            if not cache:
                cache.append(wmt14._dicts(tar_path, dict_size))
            src_dict, trg_dict = cache[0]
            with tarfile.open(tar_path) as f:
                names = [m.name for m in f
                         if m.name.endswith(file_name)]
                for name in names:
                    for line in f.extractfile(name):
                        parts = line.decode("utf-8").strip().split("\t")
                        if len(parts) != 2:
                            continue
                        src_ids = [src_dict.get(w, wmt14.UNK_IDX)
                                   for w in ([wmt14.START]
                                             + parts[0].split()
                                             + [wmt14.END])]
                        trg_ids = [trg_dict.get(w, wmt14.UNK_IDX)
                                   for w in parts[1].split()]
                        if len(src_ids) > 80 or len(trg_ids) > 80:
                            continue
                        yield (src_ids,
                               [trg_dict[wmt14.START]] + trg_ids,
                               trg_ids + [trg_dict[wmt14.END]])

        return reader

    @staticmethod
    def _synthetic(dict_size, n, seed):
        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                ln = int(r.randint(4, 12))
                body = r.randint(3, dict_size, ln)
                src = [0] + [int(x) for x in body] + [1]
                # learnable structure: trg token = succ(src token),
                # wrapped past the 3 reserved ids
                trg = [3 + (int(x) - 2) % (dict_size - 3) for x in body]
                yield src, [0] + trg, trg + [1]

        return reader

    @staticmethod
    def train(dict_size, data_dir=None, n=2000, seed=16):
        tp = wmt14._tar(data_dir)
        if tp:
            return wmt14.reader_creator(tp, "train/train", dict_size)
        return wmt14._synthetic(dict_size, n, seed)

    @staticmethod
    def test(dict_size, data_dir=None, n=200, seed=17):
        tp = wmt14._tar(data_dir)
        if tp:
            return wmt14.reader_creator(tp, "test/test", dict_size)
        return wmt14._synthetic(dict_size, n, seed)

    @staticmethod
    def get_dict(dict_size, reverse=True, data_dir=None):
        tp = wmt14._tar(data_dir)
        if tp is None:
            raise IOError("wmt14.get_dict needs the real tar "
                          "(data_dir= or $PADDLE_DATASET_HOME)")
        src, trg = wmt14._dicts(tp, dict_size)
        if reverse:
            src = {i: w for w, i in src.items()}
            trg = {i: w for w, i in trg.items()}
        return src, trg


class wmt16:
    """WMT16 en↔de multimodal subset (dataset/wmt16.py): a tar holding
    tab-separated `wmt16/train|val|test` (en \\t de).  Vocabularies are
    built from the TRAIN split by descending frequency with <s>, <e>,
    <unk> reserved as ids 0/1/2 (wmt16.py:63-84, built in memory here
    instead of cached dict files); both sides frame with <s>/<e> ids
    from the source dict (same indices in both, wmt16.py:119-122);
    src_lang 'en' or 'de' picks the column."""

    START, END, UNK = "<s>", "<e>", "<unk>"

    @staticmethod
    def _tar(data_dir):
        return _find_archive(data_dir, "wmt16",
                             ("wmt16.tar.gz", "wmt16.tgz", "wmt16.tar"))

    @staticmethod
    def build_dict(tar_path, dict_size, lang):
        from collections import defaultdict

        freq = defaultdict(int)
        with tarfile.open(tar_path) as f:
            for line in f.extractfile("wmt16/train"):
                parts = line.decode("utf-8").strip().split("\t")
                if len(parts) != 2:
                    continue
                sen = parts[0] if lang == "en" else parts[1]
                for w in sen.split():
                    freq[w] += 1
        words = [wmt16.START, wmt16.END, wmt16.UNK]
        # descending frequency; ties broken by insertion order like the
        # reference's sorted(iteritems, key=count)
        for w, _c in sorted(freq.items(), key=lambda kv: kv[1],
                            reverse=True):
            if len(words) == dict_size:
                break
            words.append(w)
        return {w: i for i, w in enumerate(words)}

    @staticmethod
    def reader_creator(tar_path, file_name, src_dict_size,
                       trg_dict_size, src_lang):
        cache = []  # vocab built once (two full train-split scans),
        # shared by every epoch's reader() call

        def reader():
            if not cache:
                trg_lang = "de" if src_lang == "en" else "en"
                cache.append((
                    wmt16.build_dict(tar_path, src_dict_size, src_lang),
                    wmt16.build_dict(tar_path, trg_dict_size,
                                     trg_lang)))
            src_dict, trg_dict = cache[0]
            start, end, unk = (src_dict[wmt16.START],
                               src_dict[wmt16.END],
                               src_dict[wmt16.UNK])
            src_col = 0 if src_lang == "en" else 1
            with tarfile.open(tar_path) as f:
                for line in f.extractfile(file_name):
                    parts = line.decode("utf-8").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_ids = ([start]
                               + [src_dict.get(w, unk)
                                  for w in parts[src_col].split()]
                               + [end])
                    trg_ids = [trg_dict.get(w, unk)
                               for w in parts[1 - src_col].split()]
                    yield (src_ids, [start] + trg_ids, trg_ids + [end])

        return reader

    @staticmethod
    def _creator(split, src_dict_size, trg_dict_size, src_lang,
                 data_dir, n, seed):
        if src_lang not in ("en", "de"):
            raise ValueError(f"wmt16: src_lang must be 'en' or 'de', "
                             f"got {src_lang!r}")
        tp = wmt16._tar(data_dir)
        if tp:
            return wmt16.reader_creator(tp, f"wmt16/{split}",
                                        src_dict_size, trg_dict_size,
                                        src_lang)
        return wmt14._synthetic(min(src_dict_size, trg_dict_size), n,
                                seed)

    @staticmethod
    def train(src_dict_size, trg_dict_size, src_lang="en",
              data_dir=None, n=2000, seed=18):
        return wmt16._creator("train", src_dict_size, trg_dict_size,
                              src_lang, data_dir, n, seed)

    @staticmethod
    def test(src_dict_size, trg_dict_size, src_lang="en",
             data_dir=None, n=200, seed=19):
        return wmt16._creator("test", src_dict_size, trg_dict_size,
                              src_lang, data_dir, n, seed)

    @staticmethod
    def validation(src_dict_size, trg_dict_size, src_lang="en",
                   data_dir=None, n=200, seed=20):
        return wmt16._creator("val", src_dict_size, trg_dict_size,
                              src_lang, data_dir, n, seed)


def padded_nmt_batches(reader, batch_size, max_src_len, max_trg_len,
                       drop_too_long=True):
    """Adapt (src_ids, trg_ids, trg_next_ids) NMT samples (wmt14/wmt16)
    to models/machine_translation.seq_to_seq_net feeds: pad to the
    static max lengths with companion seq_len vars (the padded+seq_len
    replacement for the reference's LoD batching, SURVEY.md §5.7).
    drop_too_long=False TRUNCATES over-length samples instead of
    dropping them."""

    def gen():
        buf = []
        for src, trg, nxt in reader():
            if drop_too_long and (len(src) > max_src_len
                                  or len(trg) > max_trg_len):
                continue
            buf.append((src, trg, nxt))
            if len(buf) == batch_size:
                yield _nmt_feed(buf, max_src_len, max_trg_len)
                buf = []

    return gen


def _nmt_feed(buf, max_src_len, max_trg_len):
    b = len(buf)
    src = np.zeros((b, max_src_len), np.int64)
    trg = np.zeros((b, max_trg_len), np.int64)
    nxt = np.zeros((b, max_trg_len), np.int64)
    slen = np.zeros((b,), np.int32)
    tlen = np.zeros((b,), np.int32)
    for i, (s, t, nx) in enumerate(buf):
        s, t = s[:max_src_len], t[:max_trg_len]
        nx = nx[:max_trg_len]
        src[i, :len(s)] = s
        trg[i, :len(t)] = t
        nxt[i, :len(nx)] = nx
        slen[i], tlen[i] = len(s), len(t)
    return {"src_word_id": src, "src_word_id.seq_len": slen,
            "trg_word_id": trg, "trg_word_id.seq_len": tlen,
            "trg_next_id": nxt}

class conll05:
    """CoNLL-2005 SRL (dataset/conll05.py): a tarball holding gzipped
    parallel `words` / `props` members (one token per line, blank line
    = sentence break).  Props columns are bracket-tagged spans parsed
    to B-/I-/O labels (conll05.py:108-133); one sample PER PREDICATE:

        (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
         pred_ids, mark, label_ids)

    where the five ctx slots broadcast the predicate-window words over
    the sentence, `mark` flags the window positions, and OOV maps to
    UNK_IDX=0 (conll05.py:150-200).  Dicts load from plain text files
    (one entry per line); the label dict derives classes from B-/I-
    prefixes (conll05.py:54-70), SORTED here for determinism (the
    reference enumerates set order)."""

    UNK_IDX = 0
    WORDS_MEMBER = "conll05st-release/test.wsj/words/test.wsj.words.gz"
    PROPS_MEMBER = "conll05st-release/test.wsj/props/test.wsj.props.gz"

    @staticmethod
    def load_dict(path):
        with open(path) as f:
            return {line.strip(): i for i, line in enumerate(f)
                    if line.strip()}

    @staticmethod
    def load_label_dict(path):
        tags = set()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line.startswith(("B-", "I-")):
                    tags.add(line[2:])
        d = {}
        for tag in sorted(tags):
            d["B-" + tag] = len(d)
            d["I-" + tag] = len(d)
        d["O"] = len(d)
        return d

    @staticmethod
    def corpus_reader(tar_path, words_name=WORDS_MEMBER,
                      props_name=PROPS_MEMBER):
        """Yield (sentence words, predicate word, BIO labels) per
        predicate column."""

        def parse_props_column(col):
            lbl_seq, cur, inside = [], "O", False
            for l in col:
                if l == "*" and not inside:
                    lbl_seq.append("O")
                elif l == "*" and inside:
                    lbl_seq.append("I-" + cur)
                elif l == "*)":
                    lbl_seq.append("I-" + cur)
                    inside = False
                elif "(" in l and ")" in l:
                    cur = l[1:l.find("*")]
                    lbl_seq.append("B-" + cur)
                    inside = False
                elif "(" in l:
                    cur = l[1:l.find("*")]
                    lbl_seq.append("B-" + cur)
                    inside = True
                else:
                    raise IOError(f"conll05: unexpected prop tag {l!r}")
            return lbl_seq

        def flush(sentence, seg):
            if seg:
                cols = list(zip(*seg))
                verbs = [v for v in cols[0] if v != "-"]
                for i, col in enumerate(cols[1:]):
                    yield (list(sentence), verbs[i],
                           parse_props_column(list(col)))

        def reader():
            with tarfile.open(tar_path) as tf:
                wf = gzip.GzipFile(fileobj=tf.extractfile(words_name))
                pf = gzip.GzipFile(fileobj=tf.extractfile(props_name))
                sentence, seg = [], []
                for wline, pline in zip(wf, pf):
                    word = wline.decode("utf-8").strip()
                    props = pline.decode("utf-8").strip().split()
                    if not props:  # sentence boundary
                        yield from flush(sentence, seg)
                        sentence, seg = [], []
                    else:
                        sentence.append(word)
                        seg.append(props)
                # a file ending at EOF without a trailing blank line
                # must not drop its last sentence
                yield from flush(sentence, seg)

        return reader

    @staticmethod
    def reader_creator(corpus_reader, word_dict, predicate_dict,
                       label_dict):
        def reader():
            for sentence, predicate, labels in corpus_reader():
                n = len(sentence)
                v = labels.index("B-V")
                mark = [0] * n
                ctx = {}
                for off, name in ((-2, "n2"), (-1, "n1"), (0, "0"),
                                  (1, "p1"), (2, "p2")):
                    j = v + off
                    if 0 <= j < n:
                        mark[j] = 1
                        ctx[name] = sentence[j]
                    else:
                        ctx[name] = "bos" if off < 0 else "eos"
                get = lambda w: word_dict.get(w, conll05.UNK_IDX)
                yield (
                    [get(w) for w in sentence],
                    [get(ctx["n2"])] * n, [get(ctx["n1"])] * n,
                    [get(ctx["0"])] * n, [get(ctx["p1"])] * n,
                    [get(ctx["p2"])] * n,
                    [predicate_dict[predicate]] * n,
                    mark,
                    [label_dict[l] for l in labels],
                )

        return reader

    @staticmethod
    def _files(data_dir):
        if data_dir is None:
            data_dir = _dataset_home("conll05st")
        if data_dir is None:
            return None
        paths = [os.path.join(data_dir, f) for f in
                 ("conll05st-tests.tar.gz", "wordDict.txt",
                  "verbDict.txt", "targetDict.txt")]
        return paths if all(os.path.exists(p) for p in paths) else None

    @staticmethod
    def get_dict(data_dir=None):
        files = conll05._files(data_dir)
        if files is None:
            raise IOError(
                "conll05.get_dict needs conll05st-tests.tar.gz + "
                "wordDict/verbDict/targetDict.txt (data_dir= or "
                "$PADDLE_DATASET_HOME/conll05st)")
        _tar, wd, vd, td = files
        return (conll05.load_dict(wd), conll05.load_dict(vd),
                conll05.load_label_dict(td))

    @staticmethod
    def _synthetic(n, seed, vocab=200, n_labels=9):
        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                ln = int(r.randint(4, 12))
                sent = [int(x) for x in r.randint(1, vocab, ln)]
                v = int(r.randint(0, ln))
                mark = [0] * ln
                for j in range(max(0, v - 2), min(ln, v + 3)):
                    mark[j] = 1
                lbl = [int(x) for x in r.randint(0, n_labels, ln)]
                yield (sent, [sent[max(v - 2, 0)]] * ln,
                       [sent[max(v - 1, 0)]] * ln, [sent[v]] * ln,
                       [sent[min(v + 1, ln - 1)]] * ln,
                       [sent[min(v + 2, ln - 1)]] * ln,
                       [int(r.randint(0, 50))] * ln, mark, lbl)

        return reader

    @staticmethod
    def test(n=500, seed=21, data_dir=None):
        """The reference trains on the freely-available TEST split
        (conll05.py docstring: 'Because the training dataset is not
        free, the test dataset is used for training')."""
        files = conll05._files(data_dir)
        if files:
            tar, wd, vd, td = files
            return conll05.reader_creator(
                conll05.corpus_reader(tar), conll05.load_dict(wd),
                conll05.load_dict(vd), conll05.load_label_dict(td))
        return conll05._synthetic(n, seed)

class mq2007:
    """LETOR 4.0 MQ2007 learning-to-rank (dataset/mq2007.py): text
    lines `rel qid:N 1:v 2:v ... 46:v #docid...` (48 space-split parts
    before the comment, mq2007.py:92-103).  Queries group by qid,
    docs sort by relevance desc; query_filter drops queries whose
    relevances are ALL zero (the reference filter, mq2007.py:250 —
    note it does NOT validate the {0,1,2} label range, and a
    constant-positive query legally yields zero pairwise pairs).
    Formats: pointwise (rel, vec), pairwise (1, better_vec, worse_vec)
    over all C(n,2) ordered pairs, listwise ((n,1) rels, (n,46)
    vecs)."""

    N_FEATURES = 46

    @staticmethod
    def parse_line(text):
        comment = text.find("#")
        line = (text[:comment] if comment != -1 else text).strip()
        parts = line.split()
        if len(parts) != 2 + mq2007.N_FEATURES:
            raise IOError(
                f"mq2007: expect {2 + mq2007.N_FEATURES} space-split "
                f"parts, got {len(parts)}: {text[:60]!r}")
        rel = int(parts[0])
        qid = int(parts[1].split(":")[1])
        vec = [float(p.split(":")[1]) for p in parts[2:]]
        return rel, qid, vec

    @staticmethod
    def load_from_text(path):
        """→ list of (qid, [(rel, vec), ...]) in file order."""
        groups, order = {}, []
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                rel, qid, vec = mq2007.parse_line(line)
                if qid not in groups:
                    groups[qid] = []
                    order.append(qid)
                groups[qid].append((rel, vec))
        return [(q, groups[q]) for q in order]

    FORMATS = ("pointwise", "pairwise", "listwise")

    @staticmethod
    def query_filter(groups):
        """Drop queries whose documents are ALL relevance 0 (the
        reference query_filter, mq2007.py:250 — a zero-sum querylist
        has no ranking signal)."""
        return [(q, docs) for q, docs in groups
                if sum(d[0] for d in docs) != 0]

    @staticmethod
    def _emit(docs, format):
        """One query's docs → samples for the chosen format (shared by
        the real and synthetic paths so they can never drift)."""
        docs = sorted(docs, key=lambda d: d[0], reverse=True)
        if format == "pointwise":
            for rel, vec in docs:
                yield rel, np.asarray(vec, np.float32)
        elif format == "pairwise":
            for i in range(len(docs)):
                for j in range(i + 1, len(docs)):
                    if docs[i][0] > docs[j][0]:
                        yield (np.asarray([1], np.float32),
                               np.asarray(docs[i][1], np.float32),
                               np.asarray(docs[j][1], np.float32))
        elif format == "listwise":
            yield (np.asarray([[d[0]] for d in docs], np.float32),
                   np.asarray([d[1] for d in docs], np.float32))
        else:  # pragma: no cover — _check_format guards
            raise ValueError(f"mq2007: unknown format {format!r}")

    @staticmethod
    def reader_creator(path, format="pairwise"):
        mq2007._check_format(format)

        def reader():
            for _qid, docs in mq2007.query_filter(
                    mq2007.load_from_text(path)):
                yield from mq2007._emit(docs, format)

        return reader

    @staticmethod
    def _check_format(format):
        if format not in mq2007.FORMATS:
            raise ValueError(
                f"mq2007: unknown format {format!r} (use "
                f"{'/'.join(mq2007.FORMATS)})")

    @staticmethod
    def _file(data_dir, name):
        if data_dir is None:
            data_dir = _dataset_home("MQ2007")
        if data_dir is None:
            return None
        for cand in (os.path.join(data_dir, name),
                     os.path.join(data_dir, "MQ2007", "Fold1", name)):
            if os.path.exists(cand):
                return cand
        return None

    @staticmethod
    def _synthetic(n_queries, seed, format):
        mq2007._check_format(format)

        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n_queries):
                n = int(r.randint(3, 8))
                docs = [(int(r.randint(0, 3)),
                         r.randn(mq2007.N_FEATURES).tolist())
                        for _ in range(n)]
                if sum(d[0] for d in docs) == 0:
                    continue  # mirror query_filter
                yield from mq2007._emit(docs, format)

        return reader

    @staticmethod
    def train(format="pairwise", data_dir=None, n_queries=200, seed=22):
        p = mq2007._file(data_dir, "train.txt")
        if p:
            return mq2007.reader_creator(p, format)
        return mq2007._synthetic(n_queries, seed, format)

    @staticmethod
    def test(format="pairwise", data_dir=None, n_queries=40, seed=23):
        p = mq2007._file(data_dir, "test.txt")
        if p:
            return mq2007.reader_creator(p, format)
        return mq2007._synthetic(n_queries, seed, format)


class sentiment:
    """NLTK movie_reviews sentiment corpus (dataset/sentiment.py): a
    directory (or zip) of pos/*.txt and neg/*.txt reviews.  The word
    dict orders ALL corpus words by descending frequency
    (sentiment.py:56-74); samples are (word id list, 0=pos|1=neg)
    following the reference's category indexing."""

    @staticmethod
    def _root(data_dir):
        if data_dir is None:
            data_dir = _dataset_home("sentiment")
        if data_dir is None:
            return None
        for cand in (data_dir, os.path.join(data_dir, "movie_reviews")):
            if (os.path.isdir(os.path.join(cand, "pos"))
                    and os.path.isdir(os.path.join(cand, "neg"))):
                return cand
        return None

    @staticmethod
    def _tokenize(text):
        import re

        return re.findall(r"[a-z0-9']+|[^\sa-z0-9']", text.lower())

    @staticmethod
    def _iter_files(root, cat):
        d = os.path.join(root, cat)
        for name in sorted(os.listdir(d)):
            if name.endswith(".txt"):
                with open(os.path.join(d, name), encoding="latin-1") as f:
                    yield sentiment._tokenize(f.read())

    # one read+tokenize scan of the 2000-file corpus shared by
    # get_word_dict and every train/test reader, keyed by corpus root
    _corpus_cache: dict = {}

    @staticmethod
    def _load_corpus(root):
        if root not in sentiment._corpus_cache:
            from collections import Counter

            freq = Counter()
            per_cat = {}
            for cat in ("pos", "neg"):
                per_cat[cat] = list(sentiment._iter_files(root, cat))
                for words in per_cat[cat]:
                    freq.update(words)
            ranked = sorted(freq.items(), key=lambda kv: -kv[1])
            word_dict = [(w, i) for i, (w, _c) in enumerate(ranked)]
            sentiment._corpus_cache[root] = (per_cat, word_dict)
        return sentiment._corpus_cache[root]

    @staticmethod
    def get_word_dict(data_dir=None):
        """[(word, id)] ordered by descending corpus frequency (ties by
        first-seen order, matching the reference's stable sort)."""
        root = sentiment._root(data_dir)
        if root is None:
            raise IOError(
                "sentiment.get_word_dict needs a movie_reviews dir "
                "with pos/ and neg/ (data_dir= or "
                "$PADDLE_DATASET_HOME/sentiment)")
        return sentiment._load_corpus(root)[1]

    @staticmethod
    def reader_creator(data_dir, is_test, test_ratio=0.1):
        def reader():
            root = sentiment._root(data_dir)
            per_cat, word_dict = sentiment._load_corpus(root)
            ids = dict(word_dict)
            # split WITHIN each category so both splits keep the
            # pos/neg balance (a tail slice of the pos-then-neg list
            # would make the test split all-negative)
            for label, cat in enumerate(("pos", "neg")):
                docs = per_cat[cat]
                n_test = max(1, int(len(docs) * test_ratio))
                picked = docs[-n_test:] if is_test else docs[:-n_test]
                for words in picked:
                    yield [ids[w] for w in words], label

        return reader

    @staticmethod
    def _synthetic(n, seed, vocab=5000):
        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                ln = int(r.randint(20, 120))
                label = int(r.randint(0, 2))
                # learnable: polarity words drawn from disjoint ranges
                base = 100 + label * 200
                yield ([int(x) for x in r.randint(base, base + 200,
                                                  ln)], label)

        return reader

    @staticmethod
    def train(n=1800, seed=24, data_dir=None):
        if sentiment._root(data_dir or _dataset_home("sentiment")):
            return sentiment.reader_creator(data_dir, is_test=False)
        return sentiment._synthetic(n, seed)

    @staticmethod
    def test(n=200, seed=25, data_dir=None):
        if sentiment._root(data_dir or _dataset_home("sentiment")):
            return sentiment.reader_creator(data_dir, is_test=True)
        return sentiment._synthetic(n, seed)


class voc2012:
    """PASCAL VOC2012 segmentation (dataset/voc2012.py): the VOCdevkit
    tar with ImageSets/Segmentation/{train,val,trainval}.txt name
    lists, JPEGImages/<name>.jpg and SegmentationClass/<name>.png
    (voc2012.py:37-39).  Yields (HWC uint8 image, HW uint8 class-index
    mask) — the palette png decodes to class indices."""

    SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"

    @staticmethod
    def _tar(data_dir):
        return _find_archive(
            data_dir, "voc2012",
            ("VOCtrainval_11-May-2012.tar", "VOC2012.tar",
             "voc2012.tar"))

    @staticmethod
    def reader_creator(tar_path, sub_name):
        import io

        def reader():
            from PIL import Image

            with tarfile.open(tar_path) as t:
                names = t.extractfile(
                    voc2012.SET_FILE.format(sub_name)).read()
                for name in names.decode("utf-8").split():
                    img = t.extractfile(
                        voc2012.DATA_FILE.format(name)).read()
                    lbl = t.extractfile(
                        voc2012.LABEL_FILE.format(name)).read()
                    im = np.asarray(
                        Image.open(io.BytesIO(img)).convert("RGB"),
                        np.uint8)
                    # palette png: pixel values ARE the class indices
                    mask = np.asarray(Image.open(io.BytesIO(lbl)),
                                      np.uint8)
                    yield im, mask

        return reader

    @staticmethod
    def _synthetic(n, seed, size=64, n_classes=21):
        def reader():
            r = np.random.RandomState(seed)
            for _ in range(n):
                im = r.randint(0, 256, (size, size, 3)).astype(np.uint8)
                mask = r.randint(0, n_classes,
                                 (size, size)).astype(np.uint8)
                yield im, mask

        return reader

    @staticmethod
    def _split(sub, n, seed, data_dir):
        tp = voc2012._tar(data_dir)
        if tp:
            return voc2012.reader_creator(tp, sub)
        return voc2012._synthetic(n, seed)

    # NOTE the reference's own split mapping is train->'trainval',
    # test->'train', val->'val' (voc2012.py:69-87 — VOC's real test
    # labels are not public, so its "test" reuses the train list and
    # OVERLAPS train).  Kept verbatim for parity; use val() for an
    # untainted eval split.

    @staticmethod
    def train(n=100, seed=26, data_dir=None):
        return voc2012._split("trainval", n, seed, data_dir)

    @staticmethod
    def test(n=20, seed=27, data_dir=None):
        return voc2012._split("train", n, seed, data_dir)

    @staticmethod
    def val(n=20, seed=28, data_dir=None):
        return voc2012._split("val", n, seed, data_dir)

def padded_text_batches(reader, batch_size, max_len, drop_too_long=False):
    """Adapt (word id list, label) text-classification samples
    (sentiment / imdb) to the stacked_dynamic_lstm model feeds:
    {words (B, max_len) int64 padded, words.seq_len (B,) int32,
    label (B, 1) int64}.  Over-length samples truncate (or drop)."""

    def gen():
        buf = []
        for ids, label in reader():
            if drop_too_long and len(ids) > max_len:
                continue
            buf.append((ids[:max_len], label))
            if len(buf) == batch_size:
                words = np.zeros((batch_size, max_len), np.int64)
                lens = np.zeros((batch_size,), np.int32)
                lbl = np.zeros((batch_size, 1), np.int64)
                for i, (ids_i, y) in enumerate(buf):
                    words[i, :len(ids_i)] = ids_i
                    lens[i] = max(1, len(ids_i))
                    lbl[i, 0] = y
                yield {"words": words, "words.seq_len": lens,
                       "label": lbl}
                buf = []

    return gen


def ngram_batches(reader, batch_size, window):
    """Adapt imikolov NGRAM samples ((n,) id tuples, n = window + 1) to
    the word2vec model feeds: {context_words (B, window) int64,
    target_word (B, 1) int64} — context predicts the LAST word."""

    def gen():
        buf = []
        for gram in reader():
            if len(gram) != window + 1:
                raise ValueError(
                    f"ngram_batches(window={window}) needs "
                    f"{window + 1}-grams, got {len(gram)}")
            buf.append(gram)
            if len(buf) == batch_size:
                arr = np.asarray(buf, np.int64)
                yield {"context_words": arr[:, :window],
                       "target_word": arr[:, window:]}
                buf = []

    return gen

def srl_batches(reader, batch_size, max_length):
    """Adapt conll05 9-slot samples to the models/sequence_tagging SRL
    feeds: the 6 word-feature slots + verb + mark + target, each padded
    (B, max_length) int64 with a shared per-feature .seq_len companion.
    Over-length sentences drop (static shapes under jit)."""
    names = ("word", "ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2",
             "verb", "mark", "target")

    def gen():
        buf = []
        for sample in reader():
            if len(sample[0]) > max_length:
                continue
            buf.append(sample)
            if len(buf) == batch_size:
                feed = {}
                lens = np.asarray([len(s[0]) for s in buf], np.int32)
                for j, name in enumerate(names):
                    arr = np.zeros((batch_size, max_length), np.int64)
                    for i, s in enumerate(buf):
                        arr[i, :len(s[j])] = s[j]
                    feed[name] = arr
                    feed[f"{name}.seq_len"] = lens
                yield feed
                buf = []

    return gen
