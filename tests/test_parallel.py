"""Multi-device tests on the virtual 8-device CPU mesh.

Mirrors the reference's executor-equivalence tests
(test_parallel_executor_mnist.py pattern: same model under Executor vs
ParallelExecutor must match) and exercises collectives + FSDP + tensor
parallelism.
"""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel import (ShardingRules, all_gather, all_reduce,
                                 all_to_all, make_mesh, ppermute,
                                 reduce_scatter)


def _build_mlp():
    x = layers.data(name="x", shape=[16], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="int64")
    h = layers.fc(x, size=32, act="relu")
    logits = layers.fc(h, size=4)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return loss


def _train(compiled: bool, steps=5, reduce_mode=False):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    scope = fluid.Scope()
    rng = np.random.RandomState(3)
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss = _build_mlp()
        exe = fluid.Executor()
        exe.run(startup)
        run_target = main
        if compiled:
            bs = fluid.BuildStrategy()
            if reduce_mode:
                bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
            mesh = make_mesh({"dp": 8})
            run_target = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, build_strategy=bs, mesh=mesh)
        losses = []
        for i in range(steps):
            xv = rng.randn(32, 16).astype(np.float32)
            yv = rng.randint(0, 4, (32, 1)).astype(np.int64)
            (lv,) = exe.run(run_target, feed={"x": xv, "y": yv},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def test_data_parallel_matches_single_device():
    """Loss-parity between serial Executor and 8-way data parallel
    (reference parallel_executor_test_base.py contract)."""
    single = _train(compiled=False)
    parallel = _train(compiled=True)
    np.testing.assert_allclose(single, parallel, rtol=2e-4, atol=1e-5)


def test_fsdp_reduce_mode_matches():
    single = _train(compiled=False)
    fsdp = _train(compiled=True, reduce_mode=True)
    np.testing.assert_allclose(single, fsdp, rtol=2e-4, atol=1e-5)


def test_fsdp_actually_shards_params():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss = _build_mlp()
        exe = fluid.Executor()
        exe.run(startup)
        bs = fluid.BuildStrategy()
        bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
        mesh = make_mesh({"dp": 8})
        cp = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, build_strategy=bs, mesh=mesh)
        xv = np.zeros((16, 16), np.float32)
        yv = np.zeros((16, 1), np.int64)
        exe.run(cp, feed={"x": xv, "y": yv}, fetch_list=[loss])
        w = main.all_parameters()[0]
        val = scope.find_var(w.name)
        shard_shape = val.sharding.shard_shape(val.shape)
        assert shard_shape[0] * 8 == val.shape[0], (
            f"param not sharded: {val.sharding}")


def test_tensor_parallel_rules():
    """Megatron-style: fc weights sharded over mp; results must match the
    replicated run."""
    def build():
        x = layers.data(name="x", shape=[8], dtype="float32")
        h = layers.fc(x, size=16, act="relu",
                      param_attr=fluid.ParamAttr(name="fc1_w"))
        out = layers.fc(h, size=4, param_attr=fluid.ParamAttr(name="fc2_w"))
        return layers.mean(out)

    def run(rules=None):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 11
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), fluid.scope_guard(scope):
            loss = build()
            fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            target = main
            if rules is not None:
                bs = fluid.BuildStrategy()
                bs.sharding_rules = rules
                mesh = make_mesh({"dp": 2, "mp": 4})
                target = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name, build_strategy=bs, mesh=mesh)
            vals = []
            rng = np.random.RandomState(0)
            for _ in range(3):
                xv = rng.randn(8, 8).astype(np.float32)
                (lv,) = exe.run(target, feed={"x": xv}, fetch_list=[loss])
                vals.append(float(np.asarray(lv).reshape(-1)[0]))
        return vals

    base = run()
    tp = run(ShardingRules(rules=[
        (r"fc1_w", (None, "mp")),   # column parallel
        (r"fc2_w", ("mp", None)),   # row parallel
    ]))
    np.testing.assert_allclose(base, tp, rtol=2e-4, atol=1e-5)


def test_collectives_roundtrip():
    mesh = make_mesh({"x": 8})
    a = np.arange(32, dtype=np.float32).reshape(8, 4)
    g = np.asarray(all_gather(a, mesh, "x", shard_dim=0))
    np.testing.assert_allclose(g, a)  # gather of shards == original
    # all_reduce: 8 per-device rows -> one replicated sum
    r = np.asarray(all_reduce(a, mesh, "x", shard_dim=0))
    np.testing.assert_allclose(r, a.sum(0))
    rs = np.asarray(reduce_scatter(np.ones((8, 4), np.float32), mesh, "x"))
    np.testing.assert_allclose(rs, 8.0)
    # ring permute shifts shards by one
    perm = [(i, (i + 1) % 8) for i in range(8)]
    p = np.asarray(ppermute(a, mesh, "x", perm, shard_dim=0))
    np.testing.assert_allclose(p, np.roll(a, 1, axis=0))


def test_all_to_all_head_exchange():
    mesh = make_mesh({"x": 4})
    # (heads=4, seq=8, d=2): sharded on heads (dim 0) -> sharded on seq
    # (dim 1).  The GLOBAL value is invariant — all_to_all is a resharding
    # (Ulysses head<->sequence exchange), not a data transform.
    a = np.arange(4 * 8 * 2, dtype=np.float32).reshape(4, 8, 2)
    out = all_to_all(a, mesh, "x", split_dim=1, concat_dim=0)
    np.testing.assert_allclose(np.asarray(out), a)
    # and the output is now sharded along dim 1
    shard_shape = out.sharding.shard_shape(out.shape)
    assert shard_shape == (4, 2, 2), shard_shape
