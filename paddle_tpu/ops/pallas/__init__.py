"""Pallas TPU kernels — the custom-kernel tier.

Analog of the reference's hand-written CUDA kernels and JIT codegen tier
(operators/math/*.cu, operators/jit/ xbyak codegen, SURVEY.md §2.2): ops
whose fusion XLA can't do on its own get tiled Pallas implementations.
"""

import contextlib

# tests/test_pallas_lowering.py exports these kernels with
# jax.export(platforms=["tpu"]) FROM a CPU host to validate the
# Pallas->Mosaic lowering without a chip.  The interpret gate resolves
# from the CURRENT backend at trace time, so without an override the
# export would serialize the interpreter path and the check would be
# vacuous.
_force_mosaic = [False]


@contextlib.contextmanager
def force_mosaic_lowering():
    """Force interpret=False regardless of backend, so a cross-platform
    jax.export actually runs the Mosaic lowering rules."""
    _force_mosaic[0] = True
    try:
        yield
    finally:
        _force_mosaic[0] = False


def interpret() -> bool:
    """Pallas kernels compile only on TPU; on the CPU backend (tests,
    virtual meshes) they run through the Pallas interpreter so the same
    code path is exercised everywhere.  force_mosaic_lowering()
    overrides for cross-platform jax.export TPU-lowering checks."""
    import jax

    if _force_mosaic[0]:
        return False
    return jax.default_backend() != "tpu"


# kernel-name -> cost function registry (observe/cost.py injection
# point).  A cost fn maps the custom call's actual operand/result
# shapes to the kernel's DENSE-EQUIVALENT work:
#     fn(operand_shapes, result_shapes) -> (flops, bytes_or_None)
# where each shapes list holds (dims_tuple, element_bytes) pairs.
# "Dense-equivalent" is bench.py's standing MFU convention: the flop
# count of the logical math (what the non-Pallas composition would
# compute ONCE) — skipped masked blocks are not credited and backward
# recompute is not double-counted.  bytes None = use the default
# materialized-buffers model (operands + outputs once), which already
# matches how these kernels stream HBM.  Each kernel module registers
# its entries next to its DEFAULT_BLOCK_* tuning constants.
KERNEL_COSTS = {}


def register_kernel_cost(name: str, fn):
    """Declare a Pallas kernel's analytic cost; `name` must match the
    `name=` the kernel passes to `pallas_call` (the jax.named_scope
    that reaches the custom call's HLO metadata)."""
    KERNEL_COSTS[name] = fn
    return fn


def pallas_call(*args, name=None, **kw):
    """pl.pallas_call with the shared interpret gate applied, and the
    invocation wrapped in a jax.named_scope carrying the kernel's name
    — device traces then attribute custom-call time to the specific
    Pallas kernel (custom calls are otherwise opaque blobs in profiles,
    the same blindness that makes them report zero flops to XLA's cost
    analysis).  `name` also keys the KERNEL_COSTS registry: observe.cost
    finds `pallas_<name>` in the custom call's op_name and injects the
    registered (flops, bytes) there."""
    import jax
    from jax.experimental import pallas as pl

    kernel = args[0] if args else kw.get("kernel")
    if name is None:
        name = getattr(kernel, "__name__", None) or getattr(
            getattr(kernel, "func", None), "__name__", "kernel")
    inner = pl.pallas_call(*args, interpret=interpret(), **kw)

    def scoped(*call_args, **call_kw):
        with jax.named_scope(f"pallas_{name}"):
            return inner(*call_args, **call_kw)

    return scoped
