"""Detection tail (VERDICT round-2 item 6): rpn_target_assign,
generate_proposal_labels, mine_hard_examples — per-op numeric checks
against plain-numpy mirrors of the reference semantics, plus an
end-to-end RPN pipeline training test: anchors → proposals → labels →
SmoothL1 + CE losses converging on synthetic boxes.
"""

from __future__ import annotations

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers

from op_test import run_op


def _pixel_iou_np(a, b):
    area_a = (a[:, 2] - a[:, 0] + 1) * (a[:, 3] - a[:, 1] + 1)
    area_b = (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)
    out = np.zeros((a.shape[0], b.shape[0]), np.float32)
    for i in range(a.shape[0]):
        for j in range(b.shape[0]):
            x1 = max(a[i, 0], b[j, 0])
            y1 = max(a[i, 1], b[j, 1])
            x2 = min(a[i, 2], b[j, 2])
            y2 = min(a[i, 3], b[j, 3])
            inter = max(x2 - x1 + 1, 0) * max(y2 - y1 + 1, 0)
            out[i, j] = inter / (area_a[i] + area_b[j] - inter)
    return out


def _delta_np(ex, gt, w=(1, 1, 1, 1)):
    ex_w = ex[2] - ex[0] + 1
    ex_h = ex[3] - ex[1] + 1
    gt_w = gt[2] - gt[0] + 1
    gt_h = gt[3] - gt[1] + 1
    return np.array([
        ((gt[0] + 0.5 * gt_w) - (ex[0] + 0.5 * ex_w)) / ex_w / w[0],
        ((gt[1] + 0.5 * gt_h) - (ex[1] + 0.5 * ex_h)) / ex_h / w[1],
        np.log(gt_w / ex_w) / w[2],
        np.log(gt_h / ex_h) / w[3],
    ], np.float32)


# -- rpn_target_assign ------------------------------------------------------

def _rpn_inputs():
    # 6 anchors: one straddles the image boundary, two overlap gt0 well,
    # one overlaps gt1 best, two are background
    anchors = np.array([
        [0, 0, 9, 9],        # bg
        [20, 20, 39, 39],    # high IoU with gt0
        [26, 26, 45, 45],    # moderate IoU with gt0 (~0.39: ignored)
        [60, 60, 79, 79],    # best for gt1
        [-20, -20, 5, 5],    # straddles (excluded at thresh 0)
        [90, 90, 99, 99],    # bg
    ], np.float32)
    gt = np.array([[[21, 21, 40, 40], [58, 58, 81, 81]]], np.float32)
    im_info = np.array([[100, 100, 1.0]], np.float32)
    return anchors, gt, im_info


def test_rpn_target_assign_deterministic():
    anchors, gt, im_info = _rpn_inputs()
    attrs = {"rpn_batch_size_per_im": 4, "rpn_fg_fraction": 0.5,
             "rpn_positive_overlap": 0.7, "rpn_negative_overlap": 0.3,
             "rpn_straddle_thresh": 0.0, "use_random": False}
    ins = {"Anchor": anchors, "GtBoxes": gt, "ImInfo": im_info}
    loc_idx = run_op("rpn_target_assign", ins, attrs,
                     out_slot="LocationIndex")
    labels = run_op("rpn_target_assign", ins, attrs,
                    out_slot="TargetLabel")
    score_idx = run_op("rpn_target_assign", ins, attrs,
                       out_slot="ScoreIndex")
    score_w = run_op("rpn_target_assign", ins, attrs,
                     out_slot="ScoreWeight")
    tgt_bbox = run_op("rpn_target_assign", ins, attrs,
                      out_slot="TargetBBox")
    fg_num = run_op("rpn_target_assign", ins, attrs,
                    out_slot="ForegroundNumber")

    # anchor 1 (IoU≈0.81 with gt0) and anchor 3 (best for gt1) are fg;
    # budget is 2, deterministic sampling keeps ascending index order
    assert fg_num[0] == 2
    assert set(loc_idx[0].tolist()) == {1, 3}
    # score slots: 2 fg then 2 bg, all active
    assert score_w[0].sum() == 4
    assert labels[0, :2].tolist() == [1, 1]
    assert labels[0, 2:].tolist() == [0, 0]
    # bg picks must come from {0, 5} (anchor 2 is neither fg nor bg --
    # IoU 0.39 is between the thresholds; anchor 4 straddles)
    assert set(score_idx[0, 2:].tolist()) <= {0, 5}

    # regression targets match BoxToDelta against each fg's argmax gt
    iou = _pixel_iou_np(anchors, gt[0])
    for slot, aidx in enumerate(loc_idx[0].tolist()):
        expected = _delta_np(anchors[aidx], gt[0][iou[aidx].argmax()])
        np.testing.assert_allclose(tgt_bbox[0, slot], expected,
                                   rtol=1e-4, atol=1e-5)


def test_rpn_target_assign_respects_crowd_and_gt_num():
    anchors, gt, im_info = _rpn_inputs()
    # mark gt1 as crowd → anchor 3 no longer fg
    attrs = {"rpn_batch_size_per_im": 4, "rpn_fg_fraction": 0.5,
             "rpn_straddle_thresh": 0.0, "use_random": False}
    ins = {"Anchor": anchors, "GtBoxes": gt, "ImInfo": im_info,
           "IsCrowd": np.array([[0, 1]], np.int32)}
    fg_num = run_op("rpn_target_assign", ins, attrs,
                    out_slot="ForegroundNumber")
    loc_idx = run_op("rpn_target_assign", ins, attrs,
                     out_slot="LocationIndex")
    assert fg_num[0] == 1
    assert loc_idx[0, 0] == 1


# -- generate_proposal_labels -----------------------------------------------

def test_generate_proposal_labels_deterministic():
    # gts become perfect fg candidates (IoU 1 with themselves)
    gt_boxes = np.array([[[10, 10, 29, 29], [50, 50, 69, 69]]], np.float32)
    gt_classes = np.array([[3, 7]], np.int32)
    rois = np.array([[
        [11, 11, 30, 30],     # fg (high IoU with gt0)
        [200, 200, 219, 219],  # bg (zero IoU)
        [52, 51, 70, 70],     # fg (high IoU with gt1)
        [150, 0, 169, 19],    # bg
    ]], np.float32)
    im_info = np.array([[224, 224, 1.0]], np.float32)
    attrs = {"batch_size_per_im": 6, "fg_fraction": 0.5,
             "fg_thresh": 0.5, "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
             "bbox_reg_weights": [0.1, 0.1, 0.2, 0.2], "class_nums": 8,
             "use_random": False}
    ins = {"RpnRois": rois, "GtClasses": gt_classes,
           "GtBoxes": gt_boxes, "ImInfo": im_info}
    out_rois = run_op("generate_proposal_labels", ins, attrs,
                      out_slot="Rois")
    labels = run_op("generate_proposal_labels", ins, attrs,
                    out_slot="LabelsInt32")
    tgts = run_op("generate_proposal_labels", ins, attrs,
                  out_slot="BboxTargets")
    in_w = run_op("generate_proposal_labels", ins, attrs,
                  out_slot="BboxInsideWeights")
    rois_num = run_op("generate_proposal_labels", ins, attrs,
                      out_slot="RoisNum")

    # deterministic: fg budget 3; candidates are gt0, gt1, roi0, roi2 →
    # first 3 in pool order (gt rows first) = gt0, gt1, roi0; bg pool
    # is {roi1, roi3} (unsampled fg roi2 is NOT a bg candidate) → 5
    # active slots, last slot padded
    assert rois_num[0] == 5
    assert labels[0, :3].tolist() == [3, 7, 3]
    assert (labels[0, 3:5] == 0).all()         # bg slots
    assert labels[0, 5] == -1                  # padded slot
    # fg slot 2 = roi0 matched to gt0: targets land in class-3 columns
    expected = _delta_np(rois[0, 0], gt_boxes[0, 0],
                         w=(0.1, 0.1, 0.2, 0.2))
    np.testing.assert_allclose(tgts[0, 2, 12:16], expected, rtol=1e-4,
                               atol=1e-5)
    assert in_w[0, 2, 12:16].tolist() == [1, 1, 1, 1]
    assert in_w[0, 2].sum() == 4               # only that class's slots
    assert in_w[0, 3:].sum() == 0              # bg rows carry no bbox loss
    # rois are emitted at image scale
    np.testing.assert_allclose(out_rois[0, 2], rois[0, 0], rtol=1e-5)


# -- mine_hard_examples -----------------------------------------------------

def test_mine_hard_examples_max_negative():
    cls_loss = np.array([[0.9, 0.1, 0.8, 0.4, 0.7, 0.2]], np.float32)
    match = np.array([[0, -1, -1, -1, -1, -1]], np.int32)   # 1 positive
    dist = np.array([[0.9, 0.2, 0.3, 0.1, 0.8, 0.2]], np.float32)
    attrs = {"neg_pos_ratio": 3.0, "neg_dist_threshold": 0.5,
             "mining_type": "max_negative"}
    ins = {"ClsLoss": cls_loss, "MatchIndices": match,
           "MatchDist": dist}
    neg_idx = run_op("mine_hard_examples", ins, attrs,
                     out_slot="NegIndices")
    neg_mask = run_op("mine_hard_examples", ins, attrs,
                      out_slot="NegMask")
    # eligible negatives: 1, 2, 3, 5 (4 has dist 0.8 >= 0.5); budget
    # 1 pos * 3 = 3; highest losses among eligible: 2 (.8), 3 (.4), 5 (.2)
    assert neg_idx[0].tolist() == [2, 3, 5, -1, -1, -1]
    np.testing.assert_array_equal(neg_mask[0],
                                  [0, 0, 1, 1, 0, 1])


def test_mine_hard_examples_hard_example_updates_matches():
    cls_loss = np.array([[0.9, 0.1, 0.8, 0.4]], np.float32)
    loc_loss = np.array([[0.0, 0.0, 0.0, 0.5]], np.float32)
    match = np.array([[0, -1, 1, -1]], np.int32)
    dist = np.zeros((1, 4), np.float32)
    attrs = {"sample_size": 2, "mining_type": "hard_example"}
    ins = {"ClsLoss": cls_loss, "LocLoss": loc_loss,
           "MatchIndices": match, "MatchDist": dist}
    updated = run_op("mine_hard_examples", ins, attrs,
                     out_slot="UpdatedMatchIndices")
    neg_idx = run_op("mine_hard_examples", ins, attrs,
                     out_slot="NegIndices")
    # combined losses [.9, .1, .8, .9]; top-2 = {0, 3}; positive 2 not
    # selected → demoted to -1; positive 0 selected → kept; negative 3
    # selected → neg index
    assert updated[0].tolist() == [0, -1, -1, -1]
    assert neg_idx[0].tolist() == [3, -1, -1, -1]


# -- end-to-end RPN pipeline ------------------------------------------------

def test_rpn_pipeline_trains_end_to_end():
    """Anchors → conv head → rpn_target_assign → CE + SmoothL1 RPN loss
    → generate_proposals → generate_proposal_labels, trained on a fixed
    synthetic scene until the RPN loss drops substantially (the
    reference earns its detection suite in exactly this composition)."""
    np.random.seed(0)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    h = w = 8
    num_anchors = 3
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        feat = layers.data(name="feat", shape=[16, h, w], dtype="float32")
        gt_boxes = layers.data(name="gt", shape=[2, 4], dtype="float32")
        gt_classes = layers.data(name="gtc", shape=[2], dtype="int32")
        im_info = layers.data(name="im_info", shape=[3], dtype="float32")

        anchors, _vars = layers.detection.anchor_generator(
            feat, anchor_sizes=[32, 64], aspect_ratios=[1.0, 2.0],
            stride=[16.0, 16.0])
        # anchor_generator emits (H, W, A', 4); keep 3 per cell
        anchors3 = layers.slice(anchors, axes=[2], starts=[0],
                                ends=[num_anchors])
        flat_anchors = layers.reshape(anchors3, shape=[-1, 4])

        conv = layers.conv2d(feat, num_filters=16, filter_size=3,
                             padding=1, act="relu")
        scores = layers.conv2d(conv, num_filters=num_anchors,
                               filter_size=1)
        deltas = layers.conv2d(conv, num_filters=4 * num_anchors,
                               filter_size=1)
        # (N, A, H, W) → (N, H*W*A, C) aligned with anchors (H, W, A)
        score_flat = layers.reshape(
            layers.transpose(scores, perm=[0, 2, 3, 1]), shape=[0, -1, 1])
        delta_flat = layers.reshape(
            layers.transpose(
                layers.reshape(deltas, shape=[0, num_anchors, 4, h, w]),
                perm=[0, 3, 4, 1, 2]),
            shape=[0, -1, 4])

        (pred_score, pred_loc, tgt_lbl, tgt_bbox, in_w,
         score_w) = layers.detection.rpn_target_assign(
            delta_flat, score_flat, flat_anchors, None, gt_boxes, None,
            im_info, rpn_batch_size_per_im=32, rpn_fg_fraction=0.5,
            rpn_positive_overlap=0.6, rpn_negative_overlap=0.3,
            use_random=False)

        cls_loss = layers.sigmoid_cross_entropy_with_logits(
            layers.squeeze(pred_score, axes=[2]),
            layers.cast(tgt_lbl, "float32"))
        cls_loss = layers.reduce_sum(
            layers.elementwise_mul(cls_loss, score_w))
        cls_loss = layers.elementwise_div(
            cls_loss, layers.reduce_sum(score_w))
        f_slots = 16  # fg budget = 32 * 0.5
        reg_loss = layers.reduce_sum(layers.smooth_l1(
            layers.reshape(pred_loc, shape=[0, f_slots * 4]),
            layers.reshape(tgt_bbox, shape=[0, f_slots * 4]),
            inside_weight=layers.reshape(in_w, shape=[0, f_slots * 4]),
            outside_weight=layers.reshape(in_w, shape=[0, f_slots * 4])))
        reg_loss = layers.elementwise_div(
            reg_loss,
            layers.elementwise_max(
                layers.reduce_sum(score_w),
                layers.fill_constant([1], "float32", 1.0)))
        loss = layers.elementwise_add(cls_loss, reg_loss)
        fluid.optimizer.AdamOptimizer(learning_rate=0.01).minimize(loss)

        # inference branch: proposals + head labels from current scores
        probs = layers.sigmoid(score_flat)
        probs_nahw = layers.transpose(
            layers.reshape(probs, shape=[0, h, w, num_anchors]),
            perm=[0, 3, 1, 2])
        rois, rois_num = layers.detection.generate_proposals(
            probs_nahw, deltas, im_info, anchors3,
            layers.fill_constant([h, w, num_anchors, 4], "float32", 1.0),
            pre_nms_top_n=64, post_nms_top_n=16, nms_thresh=0.7,
            min_size=4.0)
        (s_rois, s_labels, s_tgts, s_inw, s_outw,
         s_num) = layers.detection.generate_proposal_labels(
            rois, gt_classes, None, gt_boxes, im_info,
            batch_size_per_im=16, fg_fraction=0.5, fg_thresh=0.5,
            class_nums=4, use_random=False, rpn_rois_num=rois_num)

        exe = fluid.Executor()
        exe.run(startup)
        feed = {
            "feat": np.random.RandomState(1).rand(
                2, 16, h, w).astype(np.float32),
            "gt": np.array([[[16, 16, 47, 47], [64, 64, 127, 127]],
                            [[32, 32, 95, 95], [0, 0, 31, 31]]],
                           np.float32),
            "gtc": np.array([[1, 2], [3, 1]], np.int32),
            "im_info": np.array([[128, 128, 1.0], [128, 128, 1.0]],
                                np.float32),
        }
        losses = []
        for _ in range(60):
            lv, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        # RPN losses fall substantially on the fixed scene
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

        # proposal-label pipeline produces consistent fixed-slot output
        rv, ln, lab = exe.run(main, feed=feed,
                              fetch_list=[s_rois, s_num, s_labels])
        assert rv.shape == (2, 16, 4)
        assert (ln > 0).all()
        assert lab.shape == (2, 16)
        assert (lab >= -1).all() and (lab < 4).all()


def test_generate_proposal_labels_no_gt_image_all_background():
    """Annotation-free image: every valid proposal becomes a background
    sample (not zero samples) — the head still trains on it."""
    gt_boxes = np.zeros((1, 2, 4), np.float32)
    gt_classes = np.zeros((1, 2), np.int32)
    rois = np.array([[[10, 10, 29, 29], [50, 50, 69, 69],
                      [0, 0, 19, 19], [30, 30, 49, 49]]], np.float32)
    im_info = np.array([[128, 128, 1.0]], np.float32)
    attrs = {"batch_size_per_im": 4, "fg_fraction": 0.25,
             "fg_thresh": 0.5, "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
             "class_nums": 4, "use_random": False}
    ins = {"RpnRois": rois, "GtClasses": gt_classes,
           "GtBoxes": gt_boxes, "ImInfo": im_info,
           "GtNum": np.array([0], np.int32)}
    rois_num = run_op("generate_proposal_labels", ins, attrs,
                      out_slot="RoisNum")
    labels = run_op("generate_proposal_labels", ins, attrs,
                    out_slot="LabelsInt32")
    assert rois_num[0] == 4
    assert (labels[0] == 0).all()


# -- polygon_box_transform + roi_perspective_transform (round-3 tail) --------

def test_polygon_box_transform_decodes_offsets():
    x = np.zeros((1, 4, 2, 3), np.float32)
    x[0, 0, 1, 2] = 1.5    # even channel: 4*w - in
    x[0, 1, 1, 2] = 2.5    # odd channel:  4*h - in
    o = run_op("polygon_box_transform", {"Input": x}, {},
               out_slot="Output")
    assert o.shape == x.shape
    np.testing.assert_allclose(o[0, 0, 1, 2], 4 * 2 - 1.5)
    np.testing.assert_allclose(o[0, 1, 1, 2], 4 * 1 - 2.5)
    # zero offsets decode to the pixel grid itself
    np.testing.assert_allclose(o[0, 0, 0], [0, 4, 8])
    np.testing.assert_allclose(o[0, 3, :, 0], [0, 4])


def test_roi_perspective_transform_axis_aligned_identity():
    """An axis-aligned square ROI whose size matches the output grid
    reduces the homography to identity: the crop comes back exactly."""
    rng = np.random.RandomState(11)
    x = rng.rand(1, 2, 8, 8).astype(np.float32)
    # corners clockwise from top-left: (1,1) (4,1) (4,4) (1,4) → 4x4
    rois = np.array([[0, 1, 1, 4, 1, 4, 4, 1, 4]], np.float32)
    o = run_op("roi_perspective_transform",
               {"X": x, "ROIs": rois},
               {"transformed_height": 4, "transformed_width": 4,
                "spatial_scale": 1.0})
    assert o.shape == (1, 2, 4, 4)
    np.testing.assert_allclose(o[0, :, :, :], x[0, :, 1:5, 1:5],
                               rtol=1e-4, atol=1e-5)


def test_roi_perspective_transform_outside_quad_is_zero():
    x = np.ones((1, 1, 8, 8), np.float32)
    # a quad much narrower than the output grid: the normalized width
    # clamps and columns beyond it fall outside the quad → zero
    rois = np.array([[0, 1, 1, 2, 1, 2, 6, 1, 6]], np.float32)
    o = run_op("roi_perspective_transform",
               {"X": x, "ROIs": rois},
               {"transformed_height": 6, "transformed_width": 6,
                "spatial_scale": 1.0})
    assert o.shape == (1, 1, 6, 6)
    assert (o[0, 0, :, -1] == 0).all()   # far columns outside the quad
    assert o[0, 0, 0, 0] == 1.0          # inside samples the map
