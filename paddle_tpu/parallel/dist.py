"""Multi-host (multi-trainer) runtime bootstrap.

TPU-native analog of the reference's nccl2 multi-trainer mode:
- `gen_nccl_id` exchanged an ncclUniqueId over its own gRPC server
  (reference: paddle/fluid/operators/distributed_ops/gen_nccl_id_op.cc:31,78)
  → here `jax.distributed.initialize` against a coordinator endpoint.
- `ParallelExecutor` then built comms with `num_trainers * ndev` ranks
  (reference: paddle/fluid/framework/parallel_executor.cc:254;
  python knobs `num_trainers`/`trainer_id` in parallel_executor.py)
  → here a hybrid mesh whose outer axes span hosts (DCN) and inner axes
  span the chips of each host (ICI); GSPMD routes collectives over the
  right fabric automatically.
- Cluster env variables keep the reference's names
  (reference: benchmark/fluid/fluid_benchmark.py:63-110 —
  PADDLE_TRAINER_ID, PADDLE_TRAINERS, PADDLE_CURRENT_ENDPOINT,
  PADDLE_TRAINER_ENDPOINTS).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np


def init_distributed(trainer_id: Optional[int] = None,
                     num_trainers: Optional[int] = None,
                     coordinator: Optional[str] = None,
                     local_device_ids=None, health: bool = True):
    """Bootstrap the multi-host runtime (gen_nccl_id analog).

    Arguments default to the reference's cluster env vars:
    PADDLE_TRAINER_ID, PADDLE_TRAINERS, PADDLE_COORDINATOR (or the first
    entry of PADDLE_TRAINER_ENDPOINTS, matching how the reference used
    trainer 0's endpoint as the NCCLID broadcast root).

    When `num_trainers > 1` the distributed HEALTH PLANE
    (resilience/health.py: heartbeats + peer-loss monitor + the gang
    poison key) starts automatically on the same KV store — existing
    multi-trainer callers inherit bounded-time failure detection for
    free; pass `health=False` to opt out (the reference's pserver
    heartbeat analog, so a dead rank becomes a structured
    PeerLostError instead of a hang in the next collective).

    Safe to call when num_trainers == 1 (no-op).  Returns
    (trainer_id, num_trainers).
    """
    import jax

    if trainer_id is None:
        trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if num_trainers is None:
        num_trainers = int(os.environ.get("PADDLE_TRAINERS", "1"))
    if num_trainers <= 1:
        return trainer_id, num_trainers
    if coordinator is None:
        coordinator = os.environ.get("PADDLE_COORDINATOR")
    if coordinator is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        coordinator = eps.split(",")[0].strip() if eps else None
    if coordinator is None:
        raise ValueError(
            "multi-trainer bootstrap needs a coordinator endpoint: pass "
            "coordinator= or set PADDLE_COORDINATOR / "
            "PADDLE_TRAINER_ENDPOINTS")
    from ..flags import FLAGS

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_trainers,
        process_id=trainer_id,
        local_device_ids=local_device_ids,
        # bound the bootstrap wait (reference FLAGS_rpc_deadline guarded
        # the gRPC client the same way; ms → s)
        initialization_timeout=max(1, int(FLAGS.rpc_deadline / 1000)),
    )
    if health:
        from ..resilience import health as _health

        _health.start_health_plane(rank=trainer_id,
                                   num_ranks=num_trainers)
    return trainer_id, num_trainers


def shutdown_distributed():
    """Tear down the multi-host runtime.  Idempotent: safe to call
    twice, and safe when init_distributed never ran (or no-op'd at
    num_trainers == 1) — teardown paths (atexit hooks, finally blocks,
    test fixtures) must never crash on a not-running runtime.  Also
    stops the health plane first so its threads don't race a dying KV
    client."""
    import jax

    from ..resilience import health as _health

    _health.stop_health_plane()
    try:
        from jax._src import distributed

        client = distributed.global_state.client
    except Exception:  # noqa: BLE001 — private API, version-dependent
        client = None
    if client is None:
        return  # never initialized (or already shut down)
    try:
        jax.distributed.shutdown()
    except RuntimeError:
        pass  # raced another teardown path: already down


def make_multihost_mesh(ici_axes: Dict[str, int],
                        dcn_axes: Optional[Dict[str, int]] = None):
    """Hybrid DCN×ICI mesh: outer `dcn_axes` span hosts/slices (slow
    fabric), inner `ici_axes` span each host's chips (fast fabric).

    Typical data-parallel-across-hosts layout:
        make_multihost_mesh({"mp": 4}, {"dp": num_hosts})
    Axis names may repeat across the two dicts ONLY if disjoint; repeated
    names are rejected — use distinct axes and reshape shardings instead.

    Replaces the reference's flat `num_trainers * ndev` NCCL rank space
    (parallel_executor.cc:254) with a topology-aware mesh.
    """
    import jax
    from jax.sharding import Mesh

    dcn_axes = dict(dcn_axes or {})
    overlap = set(dcn_axes) & set(ici_axes)
    if overlap:
        raise ValueError(f"axes {sorted(overlap)} appear in both dcn and "
                         f"ici dicts; use distinct axis names")
    if not dcn_axes:
        from .mesh import make_mesh

        return make_mesh(ici_axes)
    devs = jax.devices()
    n = int(np.prod(list(dcn_axes.values()))
            * np.prod(list(ici_axes.values())))
    if n != len(devs):
        raise ValueError(
            f"hybrid mesh axes {dcn_axes}×{ici_axes} need exactly "
            f"{n} devices, have {len(devs)}")
    if all(getattr(d, "slice_index", None) is not None for d in devs):
        # Real multi-slice topology: let mesh_utils order devices so the
        # dcn axes land on slice boundaries; config errors propagate.
        from jax.experimental import mesh_utils

        dev_mesh = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=tuple(ici_axes.values()),
            dcn_mesh_shape=tuple(dcn_axes.values()),
            devices=devs,
        )
    else:
        # CPU/virtual meshes have no slice metadata: processes enumerate
        # devices in order, so the outer (dcn) dims reshape directly.
        dev_mesh = np.asarray(devs).reshape(
            tuple(dcn_axes.values()) + tuple(ici_axes.values()))
    return Mesh(dev_mesh, tuple(dcn_axes.keys()) + tuple(ici_axes.keys()))


def global_batch(mesh, value, axis: str = "dp"):
    """Assemble a global batch array from this process's local shard.

    Every trainer passes its LOCAL numpy batch; the result is a global
    jax.Array sharded over `axis` whose global dim 0 is
    local_batch * processes-along-axis.  Feed it to Executor.run like a
    numpy array.  (Replaces the reference pattern where each trainer fed
    its own Scope and NCCL all-reduce merged gradients.)
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    value = np.asarray(value)
    spec = P(axis, *([None] * (value.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(sharding, value)
