"""Preemption tolerance: async checkpoint writing + SIGTERM drain.

Production TPU fleets preempt — v5e slices get reclaimed, hosts are
SIGTERMed mid-step — and the two halves of surviving that live here
(docs/RESILIENCE.md, preemption section):

1. **SnapshotWriter** — the async half of checkpointing.  A sharded
   save splits into a BLOCKING snapshot phase (device→host copy of the
   shards this process owns; the only part the step loop must wait
   for) and a WRITE phase (CRC, zip serialization, the cross-process
   barrier, manifest-last rename) that runs on a single background
   writer thread with a bounded queue.  Ordering guarantees:

   - one write in flight per writer: a save submitted while another is
     writing WAITS for it (never interleaves two saves' files),
   - the write phase preserves manifest-last, so a writer killed
     mid-flush leaves a torn — and therefore unloadable — directory,
     exactly like a synchronous save killed at the same spot,
   - a writer-thread failure is latched and re-raised as a structured
     `CheckpointWriteError` on the NEXT submit/wait/close — async
     saves may be deferred, never silent.

2. **Drain controller** — a SIGTERM/SIGINT handler (main-thread-only,
   same degradation contract as watchdog.Deadline) that only sets a
   flag; the training loop (contrib.Trainer) checks `drain_requested()`
   at step boundaries, finishes the in-flight step, awaits any
   in-flight async save, writes an emergency checkpoint, and raises
   `TrainingPreempted` carrying `PREEMPT_EXIT_CODE` so the wrapper
   script can exit with a code schedulers can tell from a crash.
   `request_drain()` is the injectable test/programmatic path.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .errors import CheckpointWriteError

# Distinct exit code for a drained (preempted-but-checkpointed) exit:
# outside the shell's 1/2, pytest's 1-5, and the 128+signum band a
# raw SIGTERM/SIGKILL death produces — a supervisor seeing 77 knows an
# emergency checkpoint landed and a plain relaunch resumes.
PREEMPT_EXIT_CODE = 77


class PendingSave:
    """Handle for one in-flight (or completed) async checkpoint save.

    `snapshot_ms` (the blocking device→host portion) is known at
    submit; `write_ms`/`bytes_written` fill in when the background
    write completes.  `result()` re-raises the write-phase failure as
    a structured CheckpointWriteError."""

    def __init__(self, dirname: str, snapshot_ms: float,
                 bytes_total: int):
        self.dirname = dirname
        self.snapshot_ms = snapshot_ms
        self.bytes_total = bytes_total
        self.write_ms: Optional[float] = None
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> "PendingSave":
        """Block until the write phase finishes; raise its failure."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"async checkpoint write to {self.dirname!r} did not "
                f"complete within {timeout}s")
        if self._error is not None:
            raise _as_write_error(self._error, self.dirname)
        return self

    def stats(self) -> Dict[str, Any]:
        return {"dirname": self.dirname,
                "snapshot_ms": round(self.snapshot_ms, 3),
                "write_ms": (round(self.write_ms, 3)
                             if self.write_ms is not None else None),
                "bytes": self.bytes_total}


def _as_write_error(exc: BaseException, dirname: str) -> CheckpointWriteError:
    if isinstance(exc, CheckpointWriteError):
        return exc
    return CheckpointWriteError(
        f"async checkpoint write to {dirname!r} failed: "
        f"{type(exc).__name__}: {exc}", dirname=dirname,
        cause=f"{type(exc).__name__}: {exc}")


class SnapshotWriter:
    """One background writer thread + bounded queue for async
    checkpoint saves.

    `submit(job, finalize=)` enqueues a prepared save (io.py
    `prepare_sharded_save`) whose blocking snapshot phase ALREADY ran
    on the caller's thread.  The queue is bounded: submitting while a
    write is in flight waits for it first (coalescing by completion —
    two saves never interleave, and the step loop is back to training
    the moment the new snapshot is taken).  A latched writer failure
    is raised on the next submit/wait_idle/close as
    CheckpointWriteError — use `check()` to poll it explicitly.

    `ledger`: an observe GoodputLedger — each completed write phase is
    recorded on its `ckpt_write` BACKGROUND channel (overlapped work,
    deliberately not a wall category; the blocking snapshot the step
    loop waited out is the caller's "checkpoint" phase)."""

    def __init__(self, name: str = "ckpt-writer", ledger=None):
        self._name = name
        self._ledger = ledger
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._inflight: Optional[PendingSave] = None
        self._pending_error: Optional[CheckpointWriteError] = None
        self._closed = False

    # -- failure surfacing ------------------------------------------------
    def check(self) -> None:
        """Raise (once) the failure of a previously submitted write."""
        with self._lock:
            err, self._pending_error = self._pending_error, None
        if err is not None:
            raise err

    # -- submission -------------------------------------------------------
    def submit(self, job, finalize: Optional[Callable[[], None]] = None
               ) -> PendingSave:
        """Run `job.write()` (then `finalize()`) on the writer thread.

        Blocks only until any previous write finishes (bounded queue of
        one) — the caller's snapshot is already taken, so this wait is
        the no-two-saves-interleave guarantee, not a serialization
        stall of the new save's snapshot."""
        if self._closed:
            raise RuntimeError(f"{self._name} is closed")
        self.check()
        prev = self._inflight
        if prev is not None:
            prev._done.wait()
            self._latch(prev)
            self.check()
        pending = PendingSave(job.dirname, job.snapshot_ms, job.bytes_total)

        def _run():
            t0 = time.perf_counter()
            try:
                job.write()
                if finalize is not None:
                    finalize()
            except BaseException as e:  # noqa: BLE001 — latched, re-raised
                pending._error = e
            finally:
                pending.write_ms = (time.perf_counter() - t0) * 1000.0
                if self._ledger is not None:
                    self._ledger.note_background(
                        "ckpt_write", pending.write_ms / 1000.0)
                pending._done.set()

        self._inflight = pending
        self._thread = threading.Thread(target=_run, name=self._name,
                                        daemon=True)
        self._thread.start()
        return pending

    def _latch(self, pending: PendingSave) -> None:
        if pending._error is not None and self._pending_error is None:
            with self._lock:
                self._pending_error = _as_write_error(
                    pending._error, pending.dirname)
            pending._error = None  # surfaced exactly once

    # -- completion -------------------------------------------------------
    def wait_idle(self, timeout: Optional[float] = None) -> None:
        """Block until no write is in flight; raise any latched/new
        failure.  The drain path calls this before the emergency save."""
        prev = self._inflight
        if prev is not None:
            if not prev._done.wait(timeout):
                raise TimeoutError(
                    f"async checkpoint write to {prev.dirname!r} did "
                    f"not complete within {timeout}s")
            self._latch(prev)
            self._inflight = None
        self.check()

    def close(self, timeout: Optional[float] = 60.0) -> None:
        """Flush and shut down; raises a pending failure (a run must
        not exit green with its last checkpoint silently missing)."""
        if self._closed:
            return
        self._closed = True
        self.wait_idle(timeout)


_default_writer: Optional[SnapshotWriter] = None
_default_writer_lock = threading.Lock()


def default_writer() -> SnapshotWriter:
    """Process-wide writer shared by io.save_sharded(async_=True)
    callers that do not manage their own."""
    global _default_writer
    with _default_writer_lock:
        if _default_writer is None or _default_writer._closed:
            _default_writer = SnapshotWriter()
        return _default_writer


# ---------------------------------------------------------------------------
# Drain controller (SIGTERM/SIGINT → finish step → emergency checkpoint)
# ---------------------------------------------------------------------------

_drain_event = threading.Event()
_drain_reason: List[str] = []
_installed: Dict[int, Any] = {}  # signum -> previous handler


def drain_requested() -> bool:
    return _drain_event.is_set()


def drain_reason() -> Optional[str]:
    return _drain_reason[-1] if _drain_reason else None


def request_drain(reason: str = "requested") -> None:
    """Programmatic/injectable drain trigger (what the signal handler
    calls; tests call it directly — signals are process-global)."""
    _drain_reason.append(reason)
    _drain_event.set()


def clear_drain() -> None:
    _drain_event.clear()
    _drain_reason.clear()


def install_preempt_handler(signals=None) -> bool:
    """Install the drain-flag handler for SIGTERM/SIGINT.  Returns True
    when installed; off the main thread it degrades to a recorded no-op
    (signal.signal is main-thread-only — same contract as
    watchdog.Deadline) so a worker-thread Trainer never crashes trying.
    Idempotent; `uninstall_preempt_handler` restores the previous
    handlers."""
    import signal as _signal

    if threading.current_thread() is not threading.main_thread():
        return False
    if signals is None:
        signals = (_signal.SIGTERM, _signal.SIGINT)

    def _fire(signum, frame):  # noqa: ARG001 — signal handler shape
        request_drain(reason=f"signal:{_signal.Signals(signum).name}")

    for s in signals:
        if s not in _installed:
            _installed[s] = _signal.signal(s, _fire)
    return True


def uninstall_preempt_handler() -> None:
    import signal as _signal

    if threading.current_thread() is not threading.main_thread():
        return
    for s, old in list(_installed.items()):
        _signal.signal(s, old)
        _installed.pop(s, None)
