"""Parameter/activation sharding rules.

Replaces the reference's BuildStrategy reduce modes + DistributeTranspiler
param slicing (build_strategy.h:55, distribute_transpiler.py:80 — params
sliced into blocks round-robin over pservers).  Here a rule maps var-name
patterns to PartitionSpecs; GSPMD does the slicing and inserts the
collectives.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple


class GradSyncConfig:
    """Opt-in gradient-synchronization mode for data parallelism
    (ISSUE 10 / EQuARX, arxiv 2506.17615; full scheme + error model in
    docs/DIST.md).

    mode:
      - "bf16": explicit shard_map gradient exchange (exact psum) —
        the control arm: same code path, communication and RNG layout
        as "int8" with quantization off, so A/Bs isolate quantization
        error from everything else.
      - "int8": EQuARX-style blockwise-int8 two-phase exchange
        (collectives.quantized_all_reduce_local) — ~2x fewer gradient
        bytes per phase.  Dense grads only; SparseGrad stays sparse
        (ids+rows all_gather, O(touched) — quantizing a scatter-add
        payload would compound error on hot rows); tensors below
        `min_quant_numel` ride the exact psum.
    The default (no GradSyncConfig) keeps the implicit GSPMD all-reduce
    inserted from sharding annotations alone.

    Composition (ISSUE 13): the explicit exchange spans the mesh's
    DATA axes (batch axis + fsdp/ZeRO axis) and composes with
    mp/ep-sharded params via partial-auto shard_map — on composed
    meshes int8 rides the psum-form exchange
    (collectives.quantized_all_reduce_psum; same quantization and
    error model, wire-byte saving modeled only).  The one remaining
    designed restriction: params sharded over a DATA axis (ZeRO-3
    style) raise loudly — the replicated param entry would silently
    all-gather the model (core/executor.py)."""

    MODES = ("bf16", "int8")

    def __init__(self, mode: str = "int8", block_size: int = 256,
                 min_quant_numel: int = 4096):
        if mode not in self.MODES:
            raise ValueError(
                f"grad_sync mode {mode!r} not in {self.MODES}")
        self.mode = mode
        self.block_size = int(block_size)
        self.min_quant_numel = int(min_quant_numel)

    @classmethod
    def normalize(cls, value) -> Optional["GradSyncConfig"]:
        """None | mode-string | GradSyncConfig -> GradSyncConfig|None
        (the BuildStrategy.grad_sync coercion)."""
        if value is None:
            return None
        if isinstance(value, cls):
            return value
        return cls(mode=str(value))

    def __repr__(self):
        return (f"GradSyncConfig(mode={self.mode!r}, "
                f"block_size={self.block_size}, "
                f"min_quant_numel={self.min_quant_numel})")


class ShardingRules:
    """Ordered (regex, spec) rules; first match wins.

    spec is a tuple of mesh-axis names (or None) per tensor dim, e.g.
    (None, "mp") shards dim 1 over the "mp" axis.  `default` applies to
    unmatched params (None = replicated; "fsdp" = shard dim 0 over the
    given axis when divisible).

    zero_axis (ISSUE 13, ZeRO-style hybrid parallelism): the mesh axis
    OPTIMIZER STATE shards over, composed on top of whatever spec the
    rules produce (`opt_state_spec_for`) — per-device opt-state bytes
    drop ~1/N while params stay wherever their own rules put them
    (replicated for pure dp/fsdp, mp-sharded under Megatron rules).
    Inert on meshes without the axis, so the default ("fsdp") makes
    `make_mesh({"dp": ..., "fsdp": ...})` — or a pure {"fsdp": N}
    mesh — ZeRO-1 without any rule changes.  The axis is a DATA axis:
    the batch additionally shards over it (`data_axes_for`), so fsdp=N
    behaves like dp=N plus 1/N opt state.
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, tuple]]] = None,
                 default: Optional[str] = None,
                 fsdp_axis: str = "dp",
                 zero_axis: Optional[str] = "fsdp"):
        self.rules = [(re.compile(p), spec) for p, spec in (rules or [])]
        self.default = default
        self.fsdp_axis = fsdp_axis
        self.zero_axis = zero_axis

    def spec_for(self, name: str, shape, mesh) -> tuple:
        for pat, spec in self.rules:
            if pat.search(name):
                return self._validate(spec, shape, mesh)
        if self.default == "fsdp":
            ax_size = mesh.shape[self.fsdp_axis]
            for dim, d in enumerate(shape):
                if d % ax_size == 0 and d >= ax_size:
                    spec = [None] * len(shape)
                    spec[dim] = self.fsdp_axis
                    return tuple(spec)
        return (None,) * len(shape)

    def data_axes_for(self, mesh, batch_axis: str = "dp") -> tuple:
        """The mesh axes that carry DATA parallelism: the batch axis
        plus the ZeRO axis when present (fsdp is dp with sharded
        optimizer state — the batch shards over both).  Order is the
        mesh's axis order so rank linearization is deterministic."""
        wanted = {batch_axis}
        if self.zero_axis is not None:
            wanted.add(self.zero_axis)
        return tuple(a for a in mesh.shape
                     if a in wanted and mesh.shape[a] > 1)

    def opt_state_spec_for(self, name: str, shape, mesh) -> tuple:
        """PartitionSpec dims for an OPTIMIZER-STATE var (moments,
        velocities, …): the rule-derived spec with `zero_axis` composed
        onto the first unsharded divisible dim (ZeRO-1).  Accumulators
        named `<param>.<acc>` match their param's rule, so an
        mp-sharded param's moments stay mp-sharded AND additionally
        shard over the zero axis when a dim allows it."""
        spec = list(self.spec_for(name, shape, mesh))
        za = self.zero_axis
        if za is None or mesh.shape.get(za, 1) <= 1:
            return tuple(spec)
        n = mesh.shape[za]
        for dim, (d, ax) in enumerate(zip(shape, spec)):
            if ax is None and d >= n and d % n == 0:
                spec[dim] = za
                break
        return tuple(spec)

    def feed_spec_for(self, name: str, shape, mesh,
                      batch_axis: str = "dp") -> tuple:
        """PartitionSpec dims for a FEED (the data axes of the mesh):
        dim 0 shards over `batch_axis` — plus the ZeRO/fsdp axis when
        the mesh has one (data_axes_for) — when the batch divides the
        combined degree.  GSPMD then partitions the whole forward by
        batch and inserts the gradient all-reduce implicitly (the
        ParallelExecutor AllReduce mode).  An explicit rule matching
        the feed name wins, so ragged companions or non-batch-major
        feeds can override the data-axis default.  Non-divisible (or
        scalar) feeds replicate — a final partial batch stays correct,
        it just loses the dp speedup for that one step."""
        for pat, spec in self.rules:
            if pat.search(name):
                return self._validate(spec, shape, mesh)
        axes = self.data_axes_for(mesh, batch_axis)
        n = 1
        for a in axes:
            n *= mesh.shape[a]
        if (n > 1 and len(shape) >= 1 and shape[0] > 0
                and shape[0] % n == 0):
            first = axes[0] if len(axes) == 1 else tuple(axes)
            return (first,) + (None,) * (len(shape) - 1)
        # the combined degree does not divide: fall back to the batch
        # axis alone (the dp speedup survives an fsdp-indivisible batch)
        dp = mesh.shape.get(batch_axis, 1)
        if (dp > 1 and n != dp and len(shape) >= 1 and shape[0] > 0
                and shape[0] % dp == 0):
            return (batch_axis,) + (None,) * (len(shape) - 1)
        return (None,) * len(shape)

    @staticmethod
    def _validate(spec, shape, mesh) -> tuple:
        spec = tuple(spec) + (None,) * (len(shape) - len(spec))
        out = []
        for d, ax in zip(shape, spec):
            if ax is None:
                out.append(None)
            else:
                # an axis the mesh doesn't have degrades to replicated
                # (one rule set serves dp, dp x mp and dp x mp x ep
                # meshes)
                size = mesh.shape.get(ax, 1)
                out.append(ax if size > 1 and d % size == 0 else None)
        return tuple(out)


# Ready-made rule set for the transformer/bert models in models/:
# embedding tables sharded over "mp" on the vocab dim, and the classic
# Megatron column/row pairing keyed by layer names
# (models/transformer.py): attn_qkv + ffn_in weights column-parallel
# (output dim over mp, activations stay head/hidden-sharded), attn_out +
# ffn_out row-parallel (input dim over mp) — GSPMD then inserts exactly
# one all-reduce per attention block and one per MLP block, matching
# Megatron-LM's layout instead of the column-everywhere fallback.
def megatron_transformer_rules(fsdp: bool = False,
                               moe_axis: str = "mp") -> ShardingRules:
    """moe_axis: mesh axis the expert (E) dim of MoE weights shards
    over.  "mp" (default) reuses the tensor-parallel axis — fine when
    ep and tp don't need to compose.  "ep" gives experts their OWN axis
    on a dp x mp x ep mesh (the GShard formulation): the E dim shards
    over ep AND each expert's FFN matrices shard over mp on the hidden
    dim, so expert parallelism and tensor parallelism compose
    multiplicatively.  Axes absent from the executing mesh degrade to
    replicated (see _validate), so one rule set serves every mesh."""
    if moe_axis == "mp":
        moe_rules = [
            # expert parallelism riding the tensor-parallel axis: the E
            # axis of per-expert MoE weights shards over mp (GShard
            # dispatch/combine all-to-alls are GSPMD-inserted); the
            # router gate stays replicated
            (r"moe_expert\S*\.w", ("mp", None, None)),
            (r"moe_expert\S*\.b", ("mp", None)),
        ]
    else:
        moe_rules = [
            # dedicated expert axis composing with tensor parallelism:
            # w1 (E, D, H) -> (ep, -, mp); w2 (E, H, D) -> (ep, mp, -)
            (r"moe_expert\S*\.w_0", (moe_axis, None, "mp")),
            (r"moe_expert\S*\.w_1", (moe_axis, "mp", None)),
            (r"moe_expert\S*\.w", (moe_axis, None, None)),
            (r"moe_expert\S*\.b", (moe_axis, None)),
        ]
    return ShardingRules(
        rules=[
            (r"(word_emb|src_word_emb|trg_word_emb|word_embedding|fm_emb)",
             ("mp", None)),
            (r"(attn_qkv|ffn_in)\S*\.w", (None, "mp")),
            (r"(attn_out|ffn_out)\S*\.w", ("mp", None)),
            *moe_rules,
            # any remaining fc (e.g. the softmax projection): column
            (r"fc_\d+\.w_\d+", (None, "mp")),
        ],
        default="fsdp" if fsdp else None,
    )
