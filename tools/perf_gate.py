"""Performance regression gate: compare a fresh bench artifact against
a recorded baseline with per-metric tolerances.

Two modes, matching what each environment can actually verify:

- THROUGHPUT mode (default; run where a chip produced the candidate):
  per-model comparison of mfu / tokens_per_sec / imgs_per_sec /
  examples_per_sec (regression = relative drop beyond tolerance) and
  serving compute_ms (regression = relative increase).  Exit 1 on any
  regression, with a per-metric report.  Candidates tagged `profiled`
  or `probe_hazard.probe_loop_pids` are rejected outright — profiler-
  inflated or attach-degraded numbers must never be gated (or
  baselined) as if clean.
- SCHEMA mode (--schema; the CPU-smoke half run by tools/run_ci.sh):
  validate that a bench JSON line carries the observability contract —
  metric/value/unit/vs_baseline/detail plus compile_s/retraces/
  peak_mem_bytes/run_id/git_sha (docs/OBSERVE.md), and per training
  entry the checkpoint-cost fields (ckpt_blocking_ms/ckpt_write_ms,
  docs/RESILIENCE.md), the numerics-observability fields
  (grad_norm_last / update_ratio_worst, docs/OBSERVE.md pillar 6) and
  the goodput-ledger fields (goodput / effective_mfu /
  badput_breakdown, pillar 8) — so a chip-less CI still catches a
  broken artifact shape before it burns a chip run.

Baselines load from either a raw bench JSON line/file or a driver
wrapper ({"tail": ..., "parsed": ...}); a truncated wrapper tail (the
BENCH_r05.json case) is salvaged entry-by-entry with a balanced-brace
scan so the recorded chip numbers stay usable as a gate baseline.

Usage:
    python tools/perf_gate.py --baseline BENCH_r05.json \
        --candidate fresh.json [--tol-mfu 0.05] [--tol-throughput 0.07]
    python tools/perf_gate.py --schema --candidate line.json

Exit codes: 0 pass, 1 regression/schema violation, 2 unusable inputs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# headline metrics: higher is better, keyed by per-model detail entries
# (requests_per_sec = the serving_engine offered-load line;
# tokens_per_sec + examples_per_sec both gate the scan-bound lstm
# entry — throughput, not MFU, is the tracked axis there because the
# scan path's MFU numerator counts loop bodies once, see bench_lstm;
# the per_device_* trio gates dp-mesh entries — aggregate throughput
# can mask a per-device regression when the mesh grew, so both gate)
_THROUGHPUT_KEYS = ("tokens_per_sec", "imgs_per_sec",
                    "examples_per_sec", "requests_per_sec",
                    "per_device_tokens_per_sec",
                    "per_device_imgs_per_sec",
                    "per_device_examples_per_sec")
# serving latency: lower is better
_LATENCY_KEYS = ("compute_ms",)

# every bench line (success AND failure) must carry mem_breakdown —
# None on failure lines, the per-bucket byte dict (observe.memory) on
# measured ones; presence is the schema contract
_SCHEMA_FIELDS = ("metric", "value", "unit", "vs_baseline", "detail",
                  "compile_s", "retraces", "peak_mem_bytes",
                  "mem_breakdown", "run_id", "git_sha")


def _salvage_detail(tail: str):
    """Recover per-model entries from a truncated driver `tail`: scan
    for '"name": {' and balanced-brace-parse each object, keeping the
    ones that look like bench model entries."""
    import re

    out = {}
    i = 0
    pat = re.compile(r'"([A-Za-z0-9_]+)":\s*\{')
    while True:
        m = pat.search(tail, i)
        if not m:
            break
        depth = 0
        j = m.end() - 1
        while j < len(tail):
            if tail[j] == "{":
                depth += 1
            elif tail[j] == "}":
                depth -= 1
                if depth == 0:
                    break
            j += 1
        if depth != 0:
            break  # object itself truncated: stop
        try:
            obj = json.loads(tail[m.end() - 1:j + 1])
        except json.JSONDecodeError:
            i = m.end()
            continue
        if isinstance(obj, dict) and any(
                k in obj for k in ("mfu",) + _THROUGHPUT_KEYS
                + ("p50_ms", "error")):
            out[m.group(1)] = obj
            i = j + 1
        else:
            i = m.end()
    return out


def load_bench_artifact(path: str):
    """A bench artifact dict ({metric, value, detail, ...}) from a raw
    bench line/file or a driver wrapper, salvaging truncated tails."""
    with open(path) as f:
        raw = f.read()
    obj = None
    try:
        obj = json.loads(raw)
    except json.JSONDecodeError:
        for ln in reversed(raw.splitlines()):
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                obj = json.loads(ln)
                break
            except json.JSONDecodeError:
                continue
    if obj is None:
        raise ValueError(f"{path}: no parseable JSON")
    if isinstance(obj, dict) and "detail" in obj:
        return obj
    if isinstance(obj, dict) and ("tail" in obj or "parsed" in obj):
        parsed = obj.get("parsed")
        if isinstance(parsed, dict) and "detail" in parsed:
            return parsed
        tail = obj.get("tail") or ""
        for ln in reversed(tail.splitlines()):
            ln = ln.strip()
            if ln.startswith("{"):
                try:
                    inner = json.loads(ln)
                    if "detail" in inner:
                        return inner
                except json.JSONDecodeError:
                    pass
        detail = _salvage_detail(tail)
        if detail:
            return {"metric": "salvaged", "value": None,
                    "detail": detail, "salvaged": True}
    raise ValueError(f"{path}: not a bench artifact (no detail)")


def check_schema(candidate):
    errors = [f"missing field {f!r}" for f in _SCHEMA_FIELDS
              if f not in candidate]
    if not isinstance(candidate.get("detail"), dict):
        errors.append("detail is not an object")
        return errors
    # checkpoint-cost observability (ISSUE 7): every measured TRAINING
    # entry (it carries last_loss; serving/failure lines do not) must
    # report what a sharded save at that scale steals from the step
    # loop (ckpt_blocking_ms, None when the probe itself failed) vs
    # what the async writer hides (ckpt_write_ms)
    for name, entry in candidate["detail"].items():
        if not isinstance(entry, dict) or "error" in entry:
            continue
        if "last_loss" in entry and "ckpt_blocking_ms" not in entry:
            errors.append(f"detail.{name}: training entry missing "
                          f"ckpt_blocking_ms (async-checkpoint cost "
                          f"observability)")
        if "last_loss" in entry:
            # numerics observability (observe pillar 6): a training
            # entry must carry the window's grad norm and worst-group
            # update ratio (None only when measured --no-telemetry),
            # so divergence/dead-layer evidence rides the artifact
            for field in ("grad_norm_last", "update_ratio_worst"):
                if field not in entry:
                    errors.append(f"detail.{name}: training entry "
                                  f"missing {field!r} (numerics "
                                  f"observability, docs/OBSERVE.md "
                                  f"pillar 6)")
            # wall-clock goodput (observe pillar 8): a training entry
            # must decompose its harness wall next to the headline —
            # goodput (step fraction), effective_mfu (headline x
            # goodput) and the badput_breakdown category fractions
            for field in ("goodput", "effective_mfu",
                          "badput_breakdown"):
                if field not in entry:
                    errors.append(f"detail.{name}: training entry "
                                  f"missing {field!r} (goodput "
                                  f"ledger, docs/OBSERVE.md pillar 8)")
        # span-derived phase breakdown (ISSUE 15, observe pillar 7): a
        # serving latency number without its queue/form/dispatch
        # decomposition cannot answer "where did the time go" — the
        # offered-load entries must carry the tracer-derived keys next
        # to their e2e/TTFT/TPOT numbers
        _PHASE_KEYS = {
            "serving_engine": ("queue_wait_ms_p50", "queue_wait_ms_p99",
                               "batch_form_ms_p50", "dispatch_ms_p50"),
            "serving_decode": ("join_wait_ms_p50", "dispatch_ms_p50"),
            "serving_fleet": ("join_wait_ms_p50", "dispatch_ms_p50"),
        }
        for prefix, keys in _PHASE_KEYS.items():
            if name == prefix or (name.startswith(prefix)
                                  and prefix != "serving_engine"):
                for field in keys:
                    if field not in entry:
                        errors.append(
                            f"detail.{name}: missing {field!r} "
                            f"(span-derived phase breakdown, observe "
                            f"pillar 7)")
                break
        if name.startswith("serving_fleet"):
            # fleet contract (ISSUE 14, docs/SERVING.md §fleet): a
            # replicated-serving entry must carry the offered-load
            # throughput, the failover/hedge/retry evidence, the
            # reload pause, and the fleet-wide zero-recompile proof —
            # a req/s number that silently dropped requests or
            # recompiled mid-roll is not a resilience number
            for field in ("requests_per_sec", "failover_count",
                          "hedged", "retried", "reload_pause_ms",
                          "post_warmup_compiles"):
                if field not in entry:
                    errors.append(f"detail.{name}: fleet entry "
                                  f"missing {field!r} (fleet "
                                  f"resilience contract)")
            if entry.get("post_warmup_compiles"):
                errors.append(
                    f"detail.{name}: {entry['post_warmup_compiles']} "
                    f"post-warmup compile(s) — a shape leaked or a "
                    f"reload recompiled (the fleet-wide zero-recompile "
                    f"contract)")
            if entry.get("zero_client_failures") is False:
                errors.append(
                    f"detail.{name}: client-visible failures during "
                    f"the chaos run (the zero-failure fleet contract)")
        if name.startswith("serving_disagg"):
            # disagg contract (ISSUE 18, docs/SERVING.md §disagg): a
            # phase-disaggregated entry must carry the JOINT client
            # TTFT (submit -> first token across the prefill hop), the
            # steady decode throughput, the measured handoff tax
            # (latency + pages moved), and the fleet-wide
            # zero-recompile proof — the KV-page import must never
            # recompile the decode executable
            for field in ("ttft_p99_ms", "tokens_per_sec",
                          "handoff_ms_p50", "pages_transferred",
                          "post_warmup_compiles"):
                if field not in entry:
                    errors.append(f"detail.{name}: disagg entry "
                                  f"missing {field!r} (disagg serving "
                                  f"contract)")
            if entry.get("post_warmup_compiles"):
                errors.append(
                    f"detail.{name}: {entry['post_warmup_compiles']} "
                    f"post-warmup compile(s) — a handoff import or "
                    f"scale event recompiled (the disagg fleet-wide "
                    f"zero-recompile contract)")
            if entry.get("zero_client_failures") is False:
                errors.append(
                    f"detail.{name}: client-visible failures during "
                    f"the disagg run (the zero-failure contract)")
            if entry.get("token_parity_vs_unified") is False:
                errors.append(
                    f"detail.{name}: disagg tokens diverged from the "
                    f"unified fleet (greedy decode must be "
                    f"bit-identical across the KV handoff)")
        if name.startswith("serving_decode"):
            # decode contract (ISSUE 12, docs/SERVING.md §decode): a
            # continuous-batching decode entry must carry the
            # steady-state throughput, the scheduler's occupancy/
            # preemption telemetry, and the zero-recompile proof —
            # a tokens/s number without them is not interpretable
            for field in ("tokens_per_sec", "slot_occupancy",
                          "kv_page_utilization", "preemptions",
                          "post_warmup_compiles"):
                if field not in entry:
                    errors.append(f"detail.{name}: decode entry "
                                  f"missing {field!r} (decode "
                                  f"telemetry contract)")
            if entry.get("post_warmup_compiles"):
                errors.append(
                    f"detail.{name}: {entry['post_warmup_compiles']} "
                    f"post-warmup compile(s) — a shape leaked across "
                    f"joins/leaves/preemptions (the zero-recompile "
                    f"decode contract)")
        if entry.get("speculate"):
            # speculative contract (ISSUE 20, docs/SERVING.md
            # §speculate): a speculative entry must carry the accept
            # rate with its k+1-bin histogram, the measured speedup
            # against the sequential twin, and the token-parity proof
            # — a speculative tokens/s number whose committed stream
            # diverged from greedy decode is wrong, not fast
            for field in ("accept_rate", "accept_hist",
                          "speculation_efficiency",
                          "speedup_vs_sequential", "token_parity",
                          "post_warmup_compiles"):
                if field not in entry:
                    errors.append(f"detail.{name}: speculative entry "
                                  f"missing {field!r} (speculative "
                                  f"decode contract)")
            if entry.get("token_parity") is False:
                errors.append(
                    f"detail.{name}: speculative tokens diverged from "
                    f"the sequential engine (verified acceptance must "
                    f"be bit-identical to greedy decode)")
            hist = entry.get("accept_hist")
            if (isinstance(hist, list)
                    and len(hist) != int(entry["speculate"]) + 1):
                errors.append(
                    f"detail.{name}: accept_hist has {len(hist)} bins "
                    f"for k={entry['speculate']} (want k+1)")
            if entry.get("post_warmup_compiles"):
                errors.append(
                    f"detail.{name}: {entry['post_warmup_compiles']} "
                    f"post-warmup compile(s) in a speculative run — "
                    f"draft/verify must compile inside the warmup "
                    f"window for ANY accept pattern")
        if "mesh" in entry:
            # mesh contract (ISSUE 10 + 13, docs/DIST.md): a multi-chip
            # entry must carry per-device AND aggregate throughput, the
            # comm-bucket bytes, and — since the fsdp/ZeRO axis — the
            # per-device optimizer-state bytes of the sharded step (a
            # mesh number without its memory footprint cannot back a
            # ZeRO claim); the mesh itself must name its axes
            for field in ("n_devices", "comm_bytes", "grad_sync",
                          "opt_state_bytes_per_device"):
                if field not in entry:
                    errors.append(f"detail.{name}: mesh entry missing "
                                  f"{field!r}")
            if not (isinstance(entry["mesh"], dict) and entry["mesh"]
                    and all(isinstance(s, int) and s >= 1
                            for s in entry["mesh"].values())):
                errors.append(f"detail.{name}: mesh entry's mesh must "
                              f"be a non-empty axis->size dict, got "
                              f"{entry['mesh']!r}")
            if not any(k.startswith("per_device_") for k in entry):
                errors.append(f"detail.{name}: mesh entry missing "
                              f"per_device_* throughput")
    return errors


def _compare_entry(name, base, cand, tol_mfu, tol_tp, tol_lat,
                   regressions, report, tol_mem=0.10, tol_ls=0.02,
                   tol_comm=0.10, tol_gp=0.05, tol_ar=0.05):
    if "error" in cand and "error" not in base:
        regressions.append(f"{name}: candidate errored: "
                           f"{cand['error']}")
        return
    if base.get("mesh") != cand.get("mesh") or \
            base.get("grad_sync") != cand.get("grad_sync"):
        # a dp entry gates only against the SAME mesh + sync mode —
        # comparing dp8 throughput to a single-chip baseline (or int8
        # to bf16) would be apples-to-oranges in both directions
        report.append(f"{name}: mesh/grad_sync mismatch "
                      f"({base.get('mesh')}/{base.get('grad_sync')} vs "
                      f"{cand.get('mesh')}/{cand.get('grad_sync')}) — "
                      f"not compared")
        return
    if cand.get("skipped_update_steps"):
        # bench honesty: a throughput number that "improved" by
        # skipping optimizer math is not a number at all
        regressions.append(
            f"{name}: {cand['skipped_update_steps']} optimizer "
            f"update(s) SKIPPED inside the measured window (non-finite "
            f"taint) — throughput/MFU not comparable")
    # base mfu can legitimately round to 0.0 (CPU-smoke dp entries);
    # only a nonzero baseline can gate a relative drop
    if base.get("mfu") and "mfu" in cand:
        drop = (base["mfu"] - cand["mfu"]) / base["mfu"]
        line = (f"{name}.mfu: {base['mfu']:.4f} -> {cand['mfu']:.4f} "
                f"({-drop:+.2%})")
        report.append(line)
        if drop > tol_mfu:
            regressions.append(line + f" exceeds tol {tol_mfu:.0%}")
    for key in _THROUGHPUT_KEYS:
        if key in base and key in cand and base[key]:
            drop = (base[key] - cand[key]) / base[key]
            line = (f"{name}.{key}: {base[key]:.1f} -> "
                    f"{cand[key]:.1f} ({-drop:+.2%})")
            report.append(line)
            if drop > tol_tp:
                regressions.append(line + f" exceeds tol {tol_tp:.0%}")
    for key in _LATENCY_KEYS:
        if key in base and key in cand and base[key]:
            rise = (cand[key] - base[key]) / base[key]
            line = (f"{name}.{key}: {base[key]:.3f} -> "
                    f"{cand[key]:.3f} ({rise:+.2%})")
            report.append(line)
            if rise > tol_lat:
                regressions.append(line + f" exceeds tol {tol_lat:.0%}")
    # peak memory: higher is worse (closer to OOM at the same shape).
    # Compared only when BOTH sides measured a buffer-assignment peak —
    # pre-r06 baselines carry no mem_breakdown and are skipped, and
    # the estimate-quality "module-shapes" fallback never gates against
    # a real buffer_assignment number (different accounting)
    bmb, cmb = base.get("mem_breakdown"), cand.get("mem_breakdown")
    if isinstance(bmb, dict) and isinstance(cmb, dict) \
            and bmb.get("peak_bytes") and cmb.get("peak_bytes") \
            and bmb.get("source") == cmb.get("source"):
        rise = (cmb["peak_bytes"] - bmb["peak_bytes"]) \
            / bmb["peak_bytes"]
        line = (f"{name}.peak_hbm: {bmb['peak_bytes'] / 1e6:.1f}MB -> "
                f"{cmb['peak_bytes'] / 1e6:.1f}MB ({rise:+.2%})")
        report.append(line)
        if rise > tol_mem:
            regressions.append(line + f" exceeds tol {tol_mem:.0%}")
    # layout traffic: the layout-bucket byte share of the step
    # (transpose/copy — the r05 longctx finding).  ABSOLUTE share-point
    # increase gates: after the head-major layout (ISSUE 8) deleted the
    # boundary transposes, a change that quietly reintroduces them is a
    # regression even when throughput noise hides it at small steps.
    bls, cls = base.get("layout_share"), cand.get("layout_share")
    if isinstance(bls, (int, float)) and isinstance(cls, (int, float)):
        rise = cls - bls
        line = (f"{name}.layout_share: {bls:.4f} -> {cls:.4f} "
                f"({rise:+.4f})")
        report.append(line)
        if rise > tol_ls:
            regressions.append(
                line + f" exceeds tol +{tol_ls:.2f} share points")
    # dp comm traffic: modeled per-device collective bytes per step
    # (same mesh + grad_sync guaranteed above).  Growth beyond
    # tolerance is a regression even when throughput noise hides it —
    # gradient-exchange bytes creeping back is exactly what the
    # quantized path exists to prevent.
    bcb, ccb = base.get("comm_bytes"), cand.get("comm_bytes")
    if isinstance(bcb, (int, float)) and isinstance(ccb, (int, float)) \
            and bcb:
        rise = (ccb - bcb) / bcb
        line = (f"{name}.comm_bytes: {bcb / 1e6:.1f}MB -> "
                f"{ccb / 1e6:.1f}MB ({rise:+.2%})")
        report.append(line)
        if rise > tol_comm:
            regressions.append(line + f" exceeds tol {tol_comm:.0%}")
    # wall-clock goodput (observe pillar 8): the step share of the
    # harness wall.  ABSOLUTE share-point drop gates, and ONLY between
    # same-shaped runs (same measured step count) — the warmup/compile
    # split scales with steps, so cross-shape goodput fractions are
    # apples-to-oranges (the same-source rule, like mem_breakdown's
    # source match above)
    bgp, cgp = base.get("goodput"), cand.get("goodput")
    if isinstance(bgp, (int, float)) and isinstance(cgp, (int, float)) \
            and base.get("steps") == cand.get("steps"):
        fall = bgp - cgp
        line = (f"{name}.goodput: {bgp:.4f} -> {cgp:.4f} "
                f"({-fall:+.4f})")
        report.append(line)
        if fall > tol_gp:
            regressions.append(
                line + f" exceeds tol -{tol_gp:.2f} share points")
    # speculative accept rate (ISSUE 20): the drafter's health number.
    # ABSOLUTE drop gates, and only between same-k speculative runs —
    # on the deterministic CPU stream the accept rate is a pure
    # function of drafter + model + prompts, so a fall means drafting
    # quality regressed even when wall-clock noise hides it.  The
    # speedup itself is NOT gated here (host-timing noise); the
    # accept rate is its noise-free proxy.
    bar, car = base.get("accept_rate"), cand.get("accept_rate")
    if isinstance(bar, (int, float)) and isinstance(car, (int, float)) \
            and base.get("speculate") == cand.get("speculate"):
        fall = bar - car
        line = (f"{name}.accept_rate: {bar:.4f} -> {car:.4f} "
                f"({-fall:+.4f})")
        report.append(line)
        if fall > tol_ar:
            regressions.append(
                line + f" exceeds tol -{tol_ar:.2f} (drafting quality "
                f"regressed)")
    # ZeRO opt-state footprint: per-device resident accumulator bytes
    # of the sharded step (same mesh + grad_sync guaranteed above) —
    # creeping back up means the fsdp sharding quietly stopped applying
    bob, cob = (base.get("opt_state_bytes_per_device"),
                cand.get("opt_state_bytes_per_device"))
    if isinstance(bob, (int, float)) and isinstance(cob, (int, float)) \
            and bob:
        rise = (cob - bob) / bob
        line = (f"{name}.opt_state_bytes_per_device: "
                f"{bob / 1e6:.1f}MB -> {cob / 1e6:.1f}MB ({rise:+.2%})")
        report.append(line)
        if rise > tol_mem:
            regressions.append(line + f" exceeds tol {tol_mem:.0%}")


def gate(baseline, candidate, tol_mfu=0.05, tol_tp=0.07, tol_lat=0.10,
         tol_mem=0.10, tol_ls=0.02, tol_comm=0.10, tol_gp=0.05,
         tol_ar=0.05, allow_missing=False):
    """(regressions, report_lines, compared_count).  Only entries whose
    device kind matches are compared — a CPU smoke candidate never
    false-fails against chip numbers."""
    regressions, report = [], []
    compared = 0
    base_detail = baseline.get("detail", {})
    cand_detail = candidate.get("detail", {})
    for name, base in sorted(base_detail.items()):
        if not isinstance(base, dict):
            continue
        cand = cand_detail.get(name)
        if cand is None:
            if not allow_missing:
                regressions.append(
                    f"{name}: present in baseline, missing from "
                    f"candidate (pass --allow-missing for partial "
                    f"--model runs)")
            continue
        bdev, cdev = base.get("device"), cand.get("device")
        if bdev and cdev and bdev != cdev:
            report.append(f"{name}: device mismatch ({bdev!r} vs "
                          f"{cdev!r}) — not compared")
            continue
        compared += 1
        _compare_entry(name, base, cand, tol_mfu, tol_tp, tol_lat,
                       regressions, report, tol_mem=tol_mem,
                       tol_ls=tol_ls, tol_comm=tol_comm, tol_gp=tol_gp,
                       tol_ar=tol_ar)
        if "int8" in base and isinstance(cand.get("int8"), dict) \
                and "error" not in base["int8"]:
            if "error" in cand["int8"]:
                regressions.append(
                    f"{name}.int8: candidate errored: "
                    f"{cand['int8']['error']}")
            else:
                _compare_entry(f"{name}.int8", base["int8"],
                               cand["int8"], tol_mfu, tol_tp, tol_lat,
                               regressions, report)
    return regressions, report, compared


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--baseline", default="BENCH_r05.json")
    p.add_argument("--candidate", required=True,
                   help="fresh bench artifact (the one JSON line, a "
                        "file holding it, or a driver wrapper)")
    p.add_argument("--schema", action="store_true",
                   help="validate the bench-line observability schema "
                        "instead of comparing numbers (CPU-smoke mode)")
    p.add_argument("--tol-mfu", type=float, default=0.05,
                   help="tolerated relative MFU drop (default 5%%)")
    p.add_argument("--tol-throughput", type=float, default=0.07,
                   help="tolerated relative throughput drop "
                        "(default 7%% — bench noise at 60 steps)")
    p.add_argument("--tol-latency", type=float, default=0.10,
                   help="tolerated relative serving-latency increase")
    p.add_argument("--tol-peak-mem", type=float, default=0.10,
                   help="tolerated relative peak-HBM increase per "
                        "entry (mem_breakdown.peak_bytes; a step "
                        "quietly growing toward OOM is a regression "
                        "even when throughput holds)")
    p.add_argument("--tol-layout-share", type=float, default=0.02,
                   help="tolerated ABSOLUTE increase in an entry's "
                        "layout_share (the layout-bucket byte "
                        "fraction, observe.cost) — transpose traffic "
                        "creeping back after the head-major layout "
                        "(ISSUE 8) is a regression even when "
                        "throughput noise hides it")
    p.add_argument("--tol-comm-bytes", type=float, default=0.10,
                   help="tolerated relative increase in a dp entry's "
                        "comm_bytes (modeled per-device collective "
                        "bytes per step, observe.cost comm bucket) — "
                        "gradient-exchange traffic creeping back is a "
                        "regression even when throughput noise hides "
                        "it.  Compared only between entries with the "
                        "same mesh AND grad_sync mode")
    p.add_argument("--tol-goodput", type=float, default=0.05,
                   help="tolerated ABSOLUTE drop in a training entry's "
                        "goodput fraction (observe pillar 8 wall-clock "
                        "ledger).  Compared only between entries that "
                        "measured the SAME step count — the harness "
                        "warmup/compile split scales with steps, so "
                        "cross-shape goodput is not comparable (the "
                        "same-source rule)")
    p.add_argument("--tol-accept-rate", type=float, default=0.05,
                   help="tolerated ABSOLUTE drop in a speculative "
                        "entry's accept_rate (ISSUE 20) — on the "
                        "deterministic CPU stream the accept rate is "
                        "a pure function of drafter + model + "
                        "prompts, so a fall means drafting quality "
                        "regressed even when timing noise hides it. "
                        "Compared only between same-k runs")
    p.add_argument("--allow-missing", action="store_true",
                   help="baseline entries absent from the candidate "
                        "are not regressions (partial --model runs)")
    args = p.parse_args()

    try:
        candidate = load_bench_artifact(args.candidate)
    except (OSError, ValueError) as e:
        print(f"perf_gate: cannot load candidate: {e}",
              file=sys.stderr)
        return 2

    if args.schema:
        errors = check_schema(candidate)
        if errors:
            print("perf_gate SCHEMA FAIL:\n  " + "\n  ".join(errors),
                  file=sys.stderr)
            return 1
        print(f"perf_gate schema OK: {args.candidate} carries "
              f"{len(_SCHEMA_FIELDS)} contract fields "
              f"(metric={candidate['metric']!r})")
        return 0

    try:
        baseline = load_bench_artifact(args.baseline)
    except (OSError, ValueError) as e:
        print(f"perf_gate: cannot load baseline: {e}", file=sys.stderr)
        return 2

    if candidate.get("profiled"):
        print("perf_gate: candidate was captured under --profile — "
              "profiler-inflated numbers are not gateable", file=sys.stderr)
        return 2
    if candidate.get("probe_hazard", {}).get("probe_loop_pids"):
        print("perf_gate: candidate ran with probe_loop.sh attached "
              "(~5x hazard) — not gateable", file=sys.stderr)
        return 2
    if candidate.get("nonfinite_flag") or \
            candidate.get("skipped_update_steps"):
        print("perf_gate: candidate measured windows contained "
              f"non-finite steps (nonfinite={candidate.get('nonfinite_steps')}, "
              f"skipped_updates={candidate.get('skipped_update_steps')})"
              " — numbers produced while training was diverging or "
              "updates were skipped are not gateable", file=sys.stderr)
        return 2

    regressions, report, compared = gate(
        baseline, candidate, tol_mfu=args.tol_mfu,
        tol_tp=args.tol_throughput, tol_lat=args.tol_latency,
        tol_mem=args.tol_peak_mem, tol_ls=args.tol_layout_share,
        tol_comm=args.tol_comm_bytes, tol_gp=args.tol_goodput,
        tol_ar=args.tol_accept_rate, allow_missing=args.allow_missing)
    for line in report:
        print("  " + line)
    if compared == 0:
        print("perf_gate: no comparable entries (device mismatch or "
              "disjoint models) — refusing to report a vacuous pass",
              file=sys.stderr)
        return 2
    if regressions:
        print("perf_gate REGRESSIONS:\n  " + "\n  ".join(regressions),
              file=sys.stderr)
        return 1
    print(f"perf_gate OK: {compared} model entr"
          f"{'y' if compared == 1 else 'ies'} within tolerance "
          f"(baseline {os.path.basename(args.baseline)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
