"""Detection operators (starter set).

TPU-native implementations of the reference detection suite's core ops
(reference: paddle/fluid/operators/detection/ — prior_box_op.cc,
box_coder_op.cc, iou_similarity_op.cc, multiclass_nms_op.cc,
yolov3_loss_op.cc; 35 files total).

Static-shape design notes:
- multiclass_nms emits a FIXED (N, keep_top_k, 6) tensor padded with -1
  labels plus a per-image valid count, instead of the reference's
  variable-length LoD output — XLA needs static shapes, and the padded
  form is what serving consumers index anyway.
- NMS suppression is an O(K²) masked matrix loop over the per-class
  top-k (lax.fori_loop), the standard accelerator formulation replacing
  the reference's sorted linked-list walk.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .common import first, opt_in, out


# ---------------------------------------------------------------------------
# prior_box
# ---------------------------------------------------------------------------

@register_op("prior_box")
def prior_box(ctx, ins, attrs):
    """SSD prior (anchor) boxes for one feature map (reference
    prior_box_op.cc).

    inputs: Input (N, C, H, W) feature map, Image (N, C, Him, Wim).
    outputs: Boxes (H, W, P, 4) normalized [xmin,ymin,xmax,ymax],
             Variances (H, W, P, 4).
    """
    feat = first(ins, "Input")
    image = first(ins, "Image")
    h, w = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", []):
        ar = float(ar)
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
        if attrs.get("flip", True) and not any(
                abs(1.0 / ar - e) < 1e-6 for e in ars):
            ars.append(1.0 / ar)
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or img_w / w
    step_h = float(attrs.get("step_h", 0.0)) or img_h / h
    offset = float(attrs.get("offset", 0.5))

    # box sizes per prior (reference order: per min_size → aspect ratios
    # then the max_size sqrt box)
    widths, heights = [], []
    for k, ms in enumerate(min_sizes):
        for ar in ars:
            widths.append(ms * ar ** 0.5)
            heights.append(ms / ar ** 0.5)
        if max_sizes:
            bs = (ms * max_sizes[k]) ** 0.5
            widths.append(bs)
            heights.append(bs)
    bw = jnp.asarray(widths) / 2.0
    bh = jnp.asarray(heights) / 2.0
    p = len(widths)

    cx = (jnp.arange(w) + offset) * step_w       # (W,)
    cy = (jnp.arange(h) + offset) * step_h       # (H,)
    cxg = jnp.broadcast_to(cx[None, :, None], (h, w, p))
    cyg = jnp.broadcast_to(cy[:, None, None], (h, w, p))
    boxes = jnp.stack(
        [(cxg - bw) / img_w, (cyg - bh) / img_h,
         (cxg + bw) / img_w, (cyg + bh) / img_h], axis=-1)
    if attrs.get("clip", True):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), (h, w, p, 4))
    return out(Boxes=boxes.astype(feat.dtype),
               Variances=var.astype(feat.dtype))


# ---------------------------------------------------------------------------
# box_coder
# ---------------------------------------------------------------------------

@register_op("box_coder")
def box_coder(ctx, ins, attrs):
    """Encode/decode boxes against priors in center-size form
    (reference box_coder_op.cc).

    PriorBox (M, 4), PriorBoxVar (M, 4) optional, TargetBox:
      encode_center_size: (N, 4) gt corner boxes → Out (N, M, 4) offsets
      decode_center_size: (N, M, 4) offsets → Out (N, M, 4) corner boxes
    """
    prior = first(ins, "PriorBox")
    pvar = opt_in(ins, "PriorBoxVar")
    target = first(ins, "TargetBox")
    code_type = attrs.get("code_type", "encode_center_size")
    norm = bool(attrs.get("box_normalized", True))
    extra = 0.0 if norm else 1.0

    pw = prior[:, 2] - prior[:, 0] + extra        # (M,)
    ph = prior[:, 3] - prior[:, 1] + extra
    pcx = prior[:, 0] + pw / 2.0
    pcy = prior[:, 1] + ph / 2.0
    if pvar is None:
        pvar = jnp.ones((prior.shape[0], 4), prior.dtype)

    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + extra   # (N,)
        th = target[:, 3] - target[:, 1] + extra
        tcx = target[:, 0] + tw / 2.0
        tcy = target[:, 1] + th / 2.0
        ox = ((tcx[:, None] - pcx[None, :]) / pw[None, :]) / pvar[None, :, 0]
        oy = ((tcy[:, None] - pcy[None, :]) / ph[None, :]) / pvar[None, :, 1]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :])) / pvar[None, :, 2]
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :])) / pvar[None, :, 3]
        o = jnp.stack([ox, oy, ow, oh], axis=-1)
    elif code_type == "decode_center_size":
        # target: (N, M, 4) deltas
        dcx = pvar[None, :, 0] * target[..., 0] * pw[None, :] + pcx[None, :]
        dcy = pvar[None, :, 1] * target[..., 1] * ph[None, :] + pcy[None, :]
        dw = jnp.exp(pvar[None, :, 2] * target[..., 2]) * pw[None, :]
        dh = jnp.exp(pvar[None, :, 3] * target[..., 3]) * ph[None, :]
        o = jnp.stack([dcx - dw / 2.0, dcy - dh / 2.0,
                       dcx + dw / 2.0 - extra, dcy + dh / 2.0 - extra],
                      axis=-1)
    else:
        raise ValueError(f"unknown code_type {code_type!r}")
    return out(OutputBox=o)


# ---------------------------------------------------------------------------
# iou_similarity
# ---------------------------------------------------------------------------

def _iou_matrix(x, y, normalized=True):
    extra = 0.0 if normalized else 1.0
    area_x = (x[:, 2] - x[:, 0] + extra) * (x[:, 3] - x[:, 1] + extra)
    area_y = (y[:, 2] - y[:, 0] + extra) * (y[:, 3] - y[:, 1] + extra)
    ix1 = jnp.maximum(x[:, None, 0], y[None, :, 0])
    iy1 = jnp.maximum(x[:, None, 1], y[None, :, 1])
    ix2 = jnp.minimum(x[:, None, 2], y[None, :, 2])
    iy2 = jnp.minimum(x[:, None, 3], y[None, :, 3])
    iw = jnp.maximum(ix2 - ix1 + extra, 0.0)
    ih = jnp.maximum(iy2 - iy1 + extra, 0.0)
    inter = iw * ih
    union = area_x[:, None] + area_y[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity")
def iou_similarity(ctx, ins, attrs):
    """Pairwise IoU (reference iou_similarity_op.cc): X (N,4), Y (M,4)
    → (N, M)."""
    x = first(ins, "X")
    y = first(ins, "Y")
    return out(Out=_iou_matrix(x, y,
                               bool(attrs.get("box_normalized", True))))


# ---------------------------------------------------------------------------
# multiclass_nms
# ---------------------------------------------------------------------------

def _nms_class(boxes, scores, score_threshold, nms_threshold, top_k,
               normalized=True, nms_eta=1.0):
    """Single-class NMS over top_k candidates: returns
    (scores, keep_mask, idx)."""
    k = min(top_k, scores.shape[0])
    top_scores, order = lax.top_k(scores, k)
    cand = boxes[order]                             # (k, 4)
    iou = _iou_matrix(cand, cand, normalized)       # (k, k)
    valid0 = top_scores > score_threshold

    def body(i, carry):
        keep, thr = carry
        # suppress i if any higher-scored kept box overlaps too much
        mask = (jnp.arange(k) < i) & keep & (iou[i] > thr)
        kept_i = keep[i] & ~jnp.any(mask)
        keep = keep.at[i].set(kept_i)
        # adaptive NMS (reference nms_eta < 1): shrink the threshold
        # after each kept candidate while it stays above 0.5
        if nms_eta < 1.0:
            thr = jnp.where(kept_i & (thr > 0.5), thr * nms_eta, thr)
        return keep, thr

    # candidate 0 is kept whenever valid, and (reference NMSFast) a kept
    # box immediately shrinks the adaptive threshold for later candidates
    thr0 = jnp.asarray(nms_threshold, jnp.float32)
    if nms_eta < 1.0:
        thr0 = jnp.where(valid0[0] & (thr0 > 0.5), thr0 * nms_eta, thr0)
    keep, _ = lax.fori_loop(1, k, body, (valid0, thr0))
    keep = keep & valid0
    return top_scores, keep, order


@register_op("multiclass_nms")
def multiclass_nms(ctx, ins, attrs):
    """reference multiclass_nms_op.cc with a static-shape contract.

    inputs: BBoxes (N, M, 4), Scores (N, C, M).
    outputs: Out (N, keep_top_k, 6) rows [label, score, x1, y1, x2, y2]
             padded with -1; NmsRoisNum (N,) valid counts.
    """
    bboxes = first(ins, "BBoxes")
    scores = first(ins, "Scores")
    background = int(attrs.get("background_label", 0))
    score_th = float(attrs.get("score_threshold", 0.0))
    nms_top_k = int(attrs.get("nms_top_k", 100))
    nms_th = float(attrs.get("nms_threshold", 0.3))
    keep_top_k = int(attrs.get("keep_top_k", 100))
    normalized = bool(attrs.get("normalized", True))
    nms_eta = float(attrs.get("nms_eta", 1.0))
    N, C, M = scores.shape
    NEG = jnp.asarray(-1e30, scores.dtype)  # suppression sentinel, below
    # any real score (keeps validity distinct from legit <=0 scores)

    def per_image(boxes, sc):
        all_scores, all_idx, all_label = [], [], []
        for c in range(C):
            if c == background:
                continue
            s, keep, order = _nms_class(boxes, sc[c], score_th, nms_th,
                                        nms_top_k, normalized, nms_eta)
            all_scores.append(jnp.where(keep, s, NEG))
            all_idx.append(order)
            all_label.append(jnp.full(s.shape, c, jnp.int32))
        cat_s = jnp.concatenate(all_scores)
        cat_i = jnp.concatenate(all_idx)
        cat_l = jnp.concatenate(all_label)
        k = min(keep_top_k, cat_s.shape[0])
        top_s, pick = lax.top_k(cat_s, k)
        valid = top_s > NEG / 2
        lab = jnp.where(valid, cat_l[pick], -1)
        bx = boxes[cat_i[pick]]
        rows = jnp.concatenate(
            [lab[:, None].astype(boxes.dtype), top_s[:, None], bx], axis=1)
        rows = jnp.where(valid[:, None], rows, -1.0)
        if k < keep_top_k:
            rows = jnp.pad(rows, ((0, keep_top_k - k), (0, 0)),
                           constant_values=-1.0)
        count = jnp.sum(valid)
        return rows, count

    rows, counts = jax.vmap(per_image)(bboxes, scores)
    return out(Out=rows, NmsRoisNum=counts.astype(jnp.int32))


# ---------------------------------------------------------------------------
# yolov3_loss
# ---------------------------------------------------------------------------

def _bce(logit, target):
    return jax.nn.softplus(logit) - target * logit


@register_op("yolov3_loss")
def yolov3_loss(ctx, ins, attrs):
    """YOLOv3 training loss (reference yolov3_loss_op.cc).

    inputs: X (N, A*(5+K), H, W) raw head output, GTBox (N, B, 4)
            normalized [cx, cy, w, h], GTLabel (N, B) int (−1 or w==0
            rows are padding).
    attrs: anchors (flat [w0,h0,w1,h1,...] in input-image pixels),
           anchor_mask (indices of this head's anchors), class_num,
           ignore_thresh, downsample_ratio.
    outputs: Loss (N,).

    Assignment follows the reference: each gt is matched to the best-IoU
    anchor over ALL anchors (shape-only IoU); the loss terms apply only
    when that anchor belongs to this head's mask.  Objectness of
    non-assigned predictions is pushed to 0 unless their IoU with some
    gt exceeds ignore_thresh.
    """
    x = first(ins, "X")
    gtbox = first(ins, "GTBox")
    gtlabel = first(ins, "GTLabel").astype(jnp.int32)
    anchors = [float(a) for a in attrs["anchors"]]
    mask = [int(m) for m in attrs.get("anchor_mask",
                                      range(len(anchors) // 2))]
    class_num = int(attrs["class_num"])
    ignore = float(attrs.get("ignore_thresh", 0.7))
    down = int(attrs.get("downsample_ratio", 32))

    N, _, H, W = x.shape
    A = len(mask)
    K = class_num
    img_h, img_w = H * down, W * down
    x = x.reshape(N, A, 5 + K, H, W)
    tx, ty = x[:, :, 0], x[:, :, 1]                 # (N, A, H, W)
    tw, th = x[:, :, 2], x[:, :, 3]
    tobj = x[:, :, 4]
    tcls = x[:, :, 5:]                              # (N, A, K, H, W)

    anchor_w = jnp.asarray([anchors[2 * m] for m in mask])
    anchor_h = jnp.asarray([anchors[2 * m + 1] for m in mask])
    all_w = jnp.asarray(anchors[0::2])
    all_h = jnp.asarray(anchors[1::2])

    B = gtbox.shape[1]
    gt_valid = (gtbox[..., 2] > 0) & (gtlabel >= 0)  # (N, B)

    # best anchor per gt by shape-only IoU (reference: gt at origin)
    gw = gtbox[..., 2] * img_w                      # (N, B)
    gh = gtbox[..., 3] * img_h
    inter = (jnp.minimum(gw[..., None], all_w) *
             jnp.minimum(gh[..., None], all_h))
    union = gw[..., None] * gh[..., None] + all_w * all_h - inter
    best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)

    gi = jnp.clip((gtbox[..., 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gtbox[..., 1] * H).astype(jnp.int32), 0, H - 1)

    # decode predictions to normalized boxes for the ignore mask
    grid_x = (jnp.arange(W)[None, None, None, :])
    grid_y = (jnp.arange(H)[None, None, :, None])
    px = (jax.nn.sigmoid(tx) + grid_x) / W          # (N, A, H, W)
    py = (jax.nn.sigmoid(ty) + grid_y) / H
    pw = jnp.exp(jnp.clip(tw, -10, 10)) * anchor_w[None, :, None, None] / img_w
    ph = jnp.exp(jnp.clip(th, -10, 10)) * anchor_h[None, :, None, None] / img_h

    def pred_gt_iou(pb, gb):
        # pb: (A, H, W, 4) cxcywh; gb: (B, 4) cxcywh → (A, H, W, B)
        px1, py1 = pb[..., 0] - pb[..., 2] / 2, pb[..., 1] - pb[..., 3] / 2
        px2, py2 = pb[..., 0] + pb[..., 2] / 2, pb[..., 1] + pb[..., 3] / 2
        gx1, gy1 = gb[:, 0] - gb[:, 2] / 2, gb[:, 1] - gb[:, 3] / 2
        gx2, gy2 = gb[:, 0] + gb[:, 2] / 2, gb[:, 1] + gb[:, 3] / 2
        ix1 = jnp.maximum(px1[..., None], gx1)
        iy1 = jnp.maximum(py1[..., None], gy1)
        ix2 = jnp.minimum(px2[..., None], gx2)
        iy2 = jnp.minimum(py2[..., None], gy2)
        iw = jnp.maximum(ix2 - ix1, 0.0)
        ih = jnp.maximum(iy2 - iy1, 0.0)
        inter = iw * ih
        pa = pb[..., 2] * pb[..., 3]
        ga = gb[:, 2] * gb[:, 3]
        return inter / jnp.maximum(pa[..., None] + ga - inter, 1e-9)

    pred_boxes = jnp.stack([px, py, pw, ph], axis=-1)  # (N, A, H, W, 4)
    iou_pg = jax.vmap(pred_gt_iou)(pred_boxes, gtbox)  # (N, A, H, W, B)
    iou_max = jnp.max(jnp.where(gt_valid[:, None, None, None, :],
                                iou_pg, 0.0), axis=-1)

    # objectness targets: scatter 1 at assigned (a, gj, gi) cells
    mask_arr = jnp.asarray(mask)
    in_head = jnp.any(best_anchor[..., None] == mask_arr, axis=-1)
    assigned = gt_valid & in_head                    # (N, B)
    local_a = jnp.argmax(
        (best_anchor[..., None] == mask_arr).astype(jnp.int32), axis=-1)

    obj_target = jnp.zeros((N, A, H, W))
    batch_ix = jnp.arange(N)[:, None]
    obj_target = obj_target.at[
        batch_ix, local_a, gj, gi].max(assigned.astype(jnp.float32))

    noobj_mask = (obj_target == 0) & (iou_max <= ignore)
    obj_loss = jnp.sum(
        _bce(tobj, 1.0) * obj_target, axis=(1, 2, 3)) + jnp.sum(
        _bce(tobj, 0.0) * noobj_mask, axis=(1, 2, 3))

    # per-gt coordinate + class losses, gathered at assigned cells
    sel = lambda arr: arr[batch_ix, local_a, gj, gi]   # (N, B)
    scale = 2.0 - gtbox[..., 2] * gtbox[..., 3]        # small-box boost
    tx_t = gtbox[..., 0] * W - gi
    ty_t = gtbox[..., 1] * H - gj
    aw = anchor_w[local_a]
    ah = anchor_h[local_a]
    tw_t = jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-9), 1e-9))
    th_t = jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-9), 1e-9))
    coord = (_bce(sel(tx), tx_t) + _bce(sel(ty), ty_t)) * scale \
        + (jnp.square(sel(tw) - tw_t)
           + jnp.square(sel(th) - th_t)) * 0.5 * scale
    cls_sel = tcls[batch_ix, local_a, :, gj, gi]       # (N, B, K)
    cls_target = jax.nn.one_hot(gtlabel, K)
    cls_loss = jnp.sum(_bce(cls_sel, cls_target), axis=-1)
    per_gt = jnp.where(assigned, coord + cls_loss, 0.0)
    loss = obj_loss + jnp.sum(per_gt, axis=1)
    return out(Loss=loss)

# ---------------------------------------------------------------------------
# anchor_generator / density_prior_box
# ---------------------------------------------------------------------------

@register_op("anchor_generator")
def anchor_generator(ctx, ins, attrs):
    """Faster-RCNN anchors for one feature map (reference
    detection/anchor_generator_op.cc): per cell, boxes of every
    (anchor_size, aspect_ratio) pair in input-image pixels.

    inputs: Input (N, C, H, W); outputs: Anchors (H, W, A, 4) pixel
    [x1,y1,x2,y2], Variances (H, W, A, 4).
    """
    feat = first(ins, "Input")
    h, w = feat.shape[2], feat.shape[3]
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    stride = [float(s) for s in attrs.get("stride", [16.0, 16.0])]
    offset = float(attrs.get("offset", 0.5))

    # reference anchor_generator_op.h:55-84 exactly: the base box comes
    # from the STRIDE area (base_w = round(sqrt(stride_w*stride_h / ar)),
    # base_h = round(base_w * ar)) scaled by anchor_size/stride; centers
    # are i*stride + offset*(stride-1); corners use (side-1)/2 — the
    # RCNN-lineage convention, checkpoint-compatible (size 32 ratio 1 at
    # stride 16 → [-8, -8, 23, 23])
    ws, hs = [], []
    for r in ratios:
        for s in sizes:
            area = stride[0] * stride[1]
            base_w = round((area / r) ** 0.5)
            base_h = round(base_w * r)
            ws.append(float(base_w) * (s / stride[0]))
            hs.append(float(base_h) * (s / stride[1]))
    bw = (jnp.asarray(ws) - 1.0) / 2.0
    bh = (jnp.asarray(hs) - 1.0) / 2.0
    a = len(ws)
    cx = jnp.arange(w) * stride[0] + offset * (stride[0] - 1)
    cy = jnp.arange(h) * stride[1] + offset * (stride[1] - 1)
    cxg = jnp.broadcast_to(cx[None, :, None], (h, w, a))
    cyg = jnp.broadcast_to(cy[:, None, None], (h, w, a))
    anchors = jnp.stack([cxg - bw, cyg - bh, cxg + bw, cyg + bh], axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances), (h, w, a, 4))
    return out(Anchors=anchors.astype(feat.dtype),
               Variances=var.astype(feat.dtype))


@register_op("density_prior_box")
def density_prior_box(ctx, ins, attrs):
    """Dense SSD priors (reference detection/density_prior_box_op.cc):
    for each fixed_size with its density d, a d×d sub-grid of shifted
    boxes per cell per fixed_ratio."""
    feat = first(ins, "Input")
    image = first(ins, "Image")
    h, w = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    fixed_sizes = [float(s) for s in attrs["fixed_sizes"]]
    fixed_ratios = [float(r) for r in attrs["fixed_ratios"]]
    densities = [int(d) for d in attrs["densities"]]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or img_w / w
    step_h = float(attrs.get("step_h", 0.0)) or img_h / h
    offset = float(attrs.get("offset", 0.5))

    # reference density_prior_box_op.h:65-90 exactly: one integer
    # step_average = int((step_w + step_h)/2) drives BOTH axes' integer
    # shift = step_average // density, and the sub-grid centers offset by
    # -step_average/2 + shift/2 + d*shift
    step_average = int((step_w + step_h) * 0.5)
    centers_x, centers_y, ws, hs = [], [], [], []
    for size, dens in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw_ = size * ratio ** 0.5
            bh_ = size / ratio ** 0.5
            shift = step_average // dens
            for dy in range(dens):
                for dx in range(dens):
                    centers_x.append(
                        -step_average / 2.0 + shift / 2.0 + dx * shift)
                    centers_y.append(
                        -step_average / 2.0 + shift / 2.0 + dy * shift)
                    ws.append(bw_ / 2.0)
                    hs.append(bh_ / 2.0)
    p = len(ws)
    dx_off = jnp.asarray(centers_x)
    dy_off = jnp.asarray(centers_y)
    bw = jnp.asarray(ws)
    bh = jnp.asarray(hs)
    cx = (jnp.arange(w) + offset) * step_w
    cy = (jnp.arange(h) + offset) * step_h
    cxg = cx[None, :, None] + dx_off[None, None, :]
    cyg = cy[:, None, None] + dy_off[None, None, :]
    cxg = jnp.broadcast_to(cxg, (h, w, p))
    cyg = jnp.broadcast_to(cyg, (h, w, p))
    boxes = jnp.stack(
        [(cxg - bw) / img_w, (cyg - bh) / img_h,
         (cxg + bw) / img_w, (cyg + bh) / img_h], axis=-1)
    if attrs.get("clip", True):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), (h, w, p, 4))
    return out(Boxes=boxes.astype(feat.dtype),
               Variances=var.astype(feat.dtype))


# ---------------------------------------------------------------------------
# box_clip / bipartite_match / target_assign
# ---------------------------------------------------------------------------

@register_op("box_clip")
def box_clip(ctx, ins, attrs):
    """Clip boxes to image extents (reference detection/box_clip_op.cc).
    Input (..., 4); ImInfo (N, 3) [h, w, scale] when batched, else clip
    to attrs im_shape."""
    boxes = first(ins, "Input")
    im_info = opt_in(ins, "ImInfo")
    if im_info is not None:
        # im_info rows are [h, w, scale] of the NETWORK input; boxes are
        # in original-image coordinates, so clip to (h/scale, w/scale)
        # (reference box_clip_op.h GetImInfo)
        scale = jnp.maximum(im_info[:, 2], 1e-6)
        hmax = im_info[:, 0] / scale - 1.0
        wmax = im_info[:, 1] / scale - 1.0
        shape = (-1,) + (1,) * (boxes.ndim - 2)
        x1 = jnp.clip(boxes[..., 0], 0.0, wmax.reshape(shape))
        y1 = jnp.clip(boxes[..., 1], 0.0, hmax.reshape(shape))
        x2 = jnp.clip(boxes[..., 2], 0.0, wmax.reshape(shape))
        y2 = jnp.clip(boxes[..., 3], 0.0, hmax.reshape(shape))
        return out(Output=jnp.stack([x1, y1, x2, y2], axis=-1))
    h, w = attrs["im_shape"]
    lo = jnp.asarray([0.0, 0.0, 0.0, 0.0])
    hi = jnp.asarray([w - 1.0, h - 1.0, w - 1.0, h - 1.0])
    return out(Output=jnp.clip(boxes, lo, hi))


@register_op("bipartite_match")
def bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching over a similarity matrix (reference
    detection/bipartite_match_op.cc BipartiteMatch): repeatedly take the
    globally-best (row, col) pair, retiring both; then (match_type
    'per_prediction') also match leftover columns whose best row clears
    dist_threshold.

    inputs: DistMat (R, C) — rows = gt, cols = priors.
    outputs: ColToRowMatchIndices (1, C) int32 (-1 unmatched),
             ColToRowMatchDist (1, C).
    """
    dist = first(ins, "DistMat")
    r, c = dist.shape
    neg = jnp.asarray(-1e9, dist.dtype)

    def body(carry, _):
        d, col_idx, col_dist = carry
        flat = jnp.argmax(d)
        i, j = flat // c, flat % c
        best = d[i, j]
        ok = best > 0
        col_idx = jnp.where(ok, col_idx.at[j].set(i.astype(jnp.int32)),
                            col_idx)
        col_dist = jnp.where(ok, col_dist.at[j].set(best), col_dist)
        d = jnp.where(ok, d.at[i, :].set(neg).at[:, j].set(neg), d)
        return (d, col_idx, col_dist), None

    init = (dist, jnp.full((c,), -1, jnp.int32),
            jnp.zeros((c,), dist.dtype))
    (d_f, col_idx, col_dist), _ = lax.scan(body, init, None,
                                           length=min(r, c))

    if attrs.get("match_type", "bipartite") == "per_prediction":
        thr = float(attrs.get("dist_threshold", 0.5))
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        extra = (col_idx < 0) & (best_val >= thr)
        col_idx = jnp.where(extra, best_row, col_idx)
        col_dist = jnp.where(extra, best_val, col_dist)
    return out(ColToRowMatchIndices=col_idx[None, :],
               ColToRowMatchDist=col_dist[None, :])


@register_op("target_assign")
def target_assign(ctx, ins, attrs):
    """Scatter per-gt attributes onto matched priors (reference
    detection/target_assign_op.cc): Out[j] = X[MatchIndices[j]] where
    matched, else mismatch_value; OutWeight 1/0.

    inputs: X (R, K) gt attributes, MatchIndices (1, C) or (C,).
    """
    x = first(ins, "X")
    match = first(ins, "MatchIndices").reshape(-1).astype(jnp.int32)
    mismatch = attrs.get("mismatch_value", 0)
    matched = match >= 0
    safe = jnp.clip(match, 0, x.shape[0] - 1)
    gathered = jnp.take(x, safe, axis=0)
    fill = jnp.full_like(gathered, mismatch)
    o = jnp.where(matched[:, None], gathered, fill)
    wt = matched.astype(jnp.float32)[:, None]
    return out(Out=o, OutWeight=wt)


# ---------------------------------------------------------------------------
# generate_proposals (RPN)
# ---------------------------------------------------------------------------

@register_op("generate_proposals")
def generate_proposals(ctx, ins, attrs):
    """RPN proposal generation (reference
    detection/generate_proposals_op.cc): decode anchor deltas, clip to
    the image, drop tiny boxes (score masked), NMS, keep post_nms_topN —
    with a static-shape contract: RpnRois is (N, post_nms_topN, 4)
    zero-padded and RpnRoisNum the valid counts.

    inputs: Scores (N, A, H, W), BboxDeltas (N, 4A, H, W),
            ImInfo (N, 3), Anchors (H, W, A, 4), Variances (H, W, A, 4).
    """
    scores = first(ins, "Scores")
    deltas = first(ins, "BboxDeltas")
    im_info = first(ins, "ImInfo")
    anchors = first(ins, "Anchors").reshape(-1, 4)
    variances = first(ins, "Variances").reshape(-1, 4)
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thresh = float(attrs.get("nms_thresh", 0.7))
    min_size = float(attrs.get("min_size", 0.1))
    eta = float(attrs.get("eta", 1.0))

    n, a, h, w = scores.shape
    total = a * h * w
    pre_n = min(pre_n, total)
    # (N, A, H, W) → (N, H*W*A) aligned with anchors (H, W, A)
    sc = jnp.transpose(scores, (0, 2, 3, 1)).reshape(n, -1)
    dl = jnp.transpose(deltas.reshape(n, a, 4, h, w),
                       (0, 3, 4, 1, 2)).reshape(n, -1, 4)

    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + aw / 2.0
    acy = anchors[:, 1] + ah / 2.0

    def per_image(s, d, info):
        cx = acx + d[:, 0] * variances[:, 0] * aw
        cy = acy + d[:, 1] * variances[:, 1] * ah
        bw = aw * jnp.exp(jnp.clip(d[:, 2] * variances[:, 2], -10, 10))
        bh = ah * jnp.exp(jnp.clip(d[:, 3] * variances[:, 3], -10, 10))
        x1 = jnp.clip(cx - bw / 2.0, 0.0, info[1] - 1.0)
        y1 = jnp.clip(cy - bh / 2.0, 0.0, info[0] - 1.0)
        x2 = jnp.clip(cx + bw / 2.0, 0.0, info[1] - 1.0)
        y2 = jnp.clip(cy + bh / 2.0, 0.0, info[0] - 1.0)
        boxes = jnp.stack([x1, y1, x2, y2], axis=1)
        # reference FilterBoxes (generate_proposals_op.cc:161-176):
        # min_size floors to 1.0, sizes measured in ORIGINAL image scale
        # ((x2-x1)/im_scale + 1), centers must lie inside the image
        msize = max(min_size, 1.0)
        scale_ = jnp.maximum(info[2], 1e-6)
        ws_orig = (x2 - x1) / scale_ + 1.0
        hs_orig = (y2 - y1) / scale_ + 1.0
        cx_c = x1 + (x2 - x1 + 1.0) / 2.0
        cy_c = y1 + (y2 - y1 + 1.0) / 2.0
        keep_size = ((ws_orig >= msize) & (hs_orig >= msize)
                     & (cx_c <= info[1]) & (cy_c <= info[0]))
        s_masked = jnp.where(keep_size, s, -1e9)
        top_s, top_i = lax.top_k(s_masked, pre_n)
        cand = boxes[top_i]
        # NMS walks the FULL pre_nms pool (reference NMS loop continues
        # until post_nms_topN survivors are collected), not just the top
        # post_n candidates — suppressed slots backfill from the pool;
        # pixel-coordinate IoU uses the +1 convention
        # (JaccardOverlap normalized=false, generate_proposals_op.cc:269)
        kept_s, keep, order = _nms_class(
            cand, top_s, -1e8, nms_thresh, pre_n, normalized=False,
            nms_eta=eta)
        sel = jnp.where(keep, kept_s, -1e30)
        final_s, pick = lax.top_k(sel, min(post_n, sel.shape[0]))
        valid = final_s > -1e29
        rois = cand[order[pick]]
        rois = jnp.where(valid[:, None], rois, 0.0)
        if rois.shape[0] < post_n:
            rois = jnp.pad(rois, ((0, post_n - rois.shape[0]), (0, 0)))
            valid = jnp.pad(valid, (0, post_n - valid.shape[0]))
        return rois, jnp.sum(valid).astype(jnp.int32)

    rois, counts = jax.vmap(per_image)(sc, dl, im_info)
    return out(RpnRois=rois, RpnRoisNum=counts)


# ---------------------------------------------------------------------------
# RPN training targets + proposal labels + hard-example mining
# (reference: detection/rpn_target_assign_op.cc,
#  detection/generate_proposal_labels_op.cc,
#  detection/mine_hard_examples_op.cc).
#
# Static-shape contract (XLA): the reference emits variable-length index
# lists (LoD); here every per-image sample budget is a FIXED slot count,
# selected candidates are compacted to the front via a stable argsort on
# (category, priority) keys, and a weight/count output marks the active
# slots.  Sampling uses uniform-random priorities from the program RNG
# instead of the reference's reservoir walk — the same "uniform random
# subset of candidates" distribution, expressible with static shapes.
# ---------------------------------------------------------------------------

def _pixel_iou(a, b):
    """(A, 4) x (G, 4) pixel-coordinate IoU with the reference's +1
    convention (bbox_util.h BboxOverlaps)."""
    area_a = (a[:, 2] - a[:, 0] + 1.0) * (a[:, 3] - a[:, 1] + 1.0)
    area_b = (b[:, 2] - b[:, 0] + 1.0) * (b[:, 3] - b[:, 1] + 1.0)
    x1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    y1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    x2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    y2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(x2 - x1 + 1.0, 0.0)
    ih = jnp.maximum(y2 - y1 + 1.0, 0.0)
    inter = iw * ih
    return inter / (area_a[:, None] + area_b[None, :] - inter)


def _box_to_delta(ex, gt, weights=None):
    """bbox_util.h BoxToDelta with normalized=false (+1 widths)."""
    ex_w = ex[:, 2] - ex[:, 0] + 1.0
    ex_h = ex[:, 3] - ex[:, 1] + 1.0
    ex_cx = ex[:, 0] + 0.5 * ex_w
    ex_cy = ex[:, 1] + 0.5 * ex_h
    gt_w = gt[:, 2] - gt[:, 0] + 1.0
    gt_h = gt[:, 3] - gt[:, 1] + 1.0
    gt_cx = gt[:, 0] + 0.5 * gt_w
    gt_cy = gt[:, 1] + 0.5 * gt_h
    d = jnp.stack([
        (gt_cx - ex_cx) / ex_w,
        (gt_cy - ex_cy) / ex_h,
        jnp.log(jnp.maximum(gt_w, 1e-6) / jnp.maximum(ex_w, 1e-6)),
        jnp.log(jnp.maximum(gt_h, 1e-6) / jnp.maximum(ex_h, 1e-6)),
    ], axis=1)
    if weights is not None:
        d = d / jnp.asarray(weights, d.dtype)[None, :]
    return d


def _sample_budget(cand_mask, budget, rng, use_random, priority=None):
    """Pick up to `budget` (traced or static) candidates from a boolean
    mask with static shapes: rank candidates by priority (uniform random
    when use_random, else ascending index like the reference's
    non-random path) and keep rank < min(budget, count).  Returns
    (selected_mask, count)."""
    n = cand_mask.shape[0]
    if priority is None:
        priority = (jax.random.uniform(rng, (n,)) if use_random
                    else -jnp.arange(n, dtype=jnp.float32))
    score = jnp.where(cand_mask, priority, -jnp.inf)
    order = jnp.argsort(-score)           # best first
    rank = jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    count = jnp.minimum(jnp.sum(cand_mask.astype(jnp.int32)),
                        jnp.asarray(budget, jnp.int32))
    sel = cand_mask & (rank < count)
    return sel, count


def _compact(masks_and_payloads, total):
    """Stable-compact rows selected by category masks to the front.

    masks_and_payloads: list of (mask (K,), category_rank) — rows with
    lower category_rank come first; within a category, index order.
    Returns `order` (total,) row indices (garbage past the active
    count) and the active count."""
    k = masks_and_payloads[0][0].shape[0]
    key = jnp.full((k,), 1e9, jnp.float32)
    for mask, cat in masks_and_payloads:
        key = jnp.where(mask, cat * float(k) + jnp.arange(k, dtype=jnp.float32),
                        key)
    order = jnp.argsort(key)
    if k < total:      # pool smaller than the slot budget: pad rows
        order = jnp.pad(order, (0, total - k))
    order = order[:total]
    count = jnp.sum(jnp.asarray(
        [jnp.sum(m.astype(jnp.int32)) for m, _ in masks_and_payloads]))
    return order, count.astype(jnp.int32)


@register_op("rpn_target_assign")
def rpn_target_assign(ctx, ins, attrs):
    """RPN anchor classification/regression targets (reference
    detection/rpn_target_assign_op.cc).  Faster-RCNN rules: positives
    are (i) per-gt max-overlap anchors and (ii) anchors with IoU >=
    rpn_positive_overlap; negatives have max IoU < rpn_negative_overlap;
    budgets rpn_fg_fraction * rpn_batch_size_per_im fg, remainder bg.

    inputs: Anchor (A, 4); GtBoxes (N, G, 4) zero-padded; GtNum (N,)
    valid counts (optional, default G); IsCrowd (N, G) optional;
    ImInfo (N, 3).
    outputs (fixed slots, F = fg budget, S = rpn_batch_size_per_im):
      LocationIndex (N, F) anchor ids, fg compacted first;
      TargetBBox (N, F, 4); BBoxInsideWeight (N, F, 4);
      ScoreIndex (N, S) anchor ids (fg then bg); TargetLabel (N, S);
      ScoreWeight (N, S) 1.0 on active slots (divergence: replaces the
      reference's variable-length LoD outputs);
      ForegroundNumber (N,) fg counts.

    Divergences (documented): uniform-random sampling replaces the
    reservoir walk; the reference's Detectron-compat bg-overwrites-fg
    quirk (rpn_target_assign_op.cc:219 'it seems here is a bug') is NOT
    replicated — selected fg anchors are excluded from bg candidates."""
    anchor = first(ins, "Anchor").astype(jnp.float32)
    gt_boxes = first(ins, "GtBoxes").astype(jnp.float32)
    gt_num = opt_in(ins, "GtNum")
    is_crowd = opt_in(ins, "IsCrowd")
    im_info = first(ins, "ImInfo").astype(jnp.float32)

    s_total = int(attrs.get("rpn_batch_size_per_im", 256))
    straddle = float(attrs.get("rpn_straddle_thresh", 0.0))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    pos_ov = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_ov = float(attrs.get("rpn_negative_overlap", 0.3))
    use_random = bool(attrs.get("use_random", True))
    f_total = int(fg_frac * s_total)

    n, g = gt_boxes.shape[0], gt_boxes.shape[1]
    a = anchor.shape[0]
    if gt_num is None:
        gt_num = jnp.full((n,), g, jnp.int32)
    if is_crowd is None:
        is_crowd = jnp.zeros((n, g), jnp.int32)
    rngs = jax.random.split(ctx.rng(), n * 2).reshape(n, 2, 2)

    def per_image(gts, gnum, crowd, info, rng2):
        im_h, im_w, im_scale = info[0], info[1], info[2]
        if straddle >= 0:
            inside = ((anchor[:, 0] >= -straddle) &
                      (anchor[:, 1] >= -straddle) &
                      (anchor[:, 2] < im_w + straddle) &
                      (anchor[:, 3] < im_h + straddle))
        else:
            inside = jnp.ones((a,), jnp.bool_)
        gt_valid = (jnp.arange(g) < gnum) & (crowd == 0)
        gts_sc = gts * im_scale
        iou = _pixel_iou(anchor, gts_sc)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        iou = jnp.where(inside[:, None], iou, -1.0)

        a2g_max = jnp.max(iou, axis=1) if g else jnp.zeros((a,))
        a2g_arg = jnp.argmax(iou, axis=1) if g else jnp.zeros((a,), jnp.int32)
        g2a_max = jnp.max(iou, axis=0)

        is_gt_best = jnp.any(
            gt_valid[None, :] & (g2a_max[None, :] > 0) &
            (jnp.abs(iou - g2a_max[None, :]) < 1e-5), axis=1)
        fg_cand = inside & (is_gt_best | (a2g_max >= pos_ov))
        fg_sel, fg_cnt = _sample_budget(fg_cand, f_total, rng2[0],
                                        use_random)
        bg_cand = inside & (a2g_max < neg_ov) & ~fg_sel
        bg_sel, bg_cnt = _sample_budget(bg_cand, s_total - fg_cnt,
                                        rng2[1], use_random)

        loc_order, _ = _compact([(fg_sel, 0.0)], f_total)
        fg_active = jnp.arange(f_total) < fg_cnt
        tgt_gt = gts_sc[a2g_arg[loc_order]]
        tgt_bbox = _box_to_delta(anchor[loc_order], tgt_gt)
        tgt_bbox = jnp.where(fg_active[:, None], tgt_bbox, 0.0)
        inside_w = jnp.where(fg_active[:, None],
                             jnp.ones((f_total, 4)), 0.0)

        score_order, score_cnt = _compact([(fg_sel, 0.0), (bg_sel, 1.0)],
                                          s_total)
        score_active = jnp.arange(s_total) < score_cnt
        labels = jnp.where(jnp.arange(s_total) < fg_cnt, 1, 0)
        return (jnp.where(fg_active, loc_order, 0).astype(jnp.int32),
                tgt_bbox, inside_w,
                jnp.where(score_active, score_order, 0).astype(jnp.int32),
                jnp.where(score_active, labels, 0).astype(jnp.int32),
                score_active.astype(jnp.float32),
                fg_cnt)

    (loc_idx, tgt_bbox, in_w, score_idx, labels, score_w,
     fg_counts) = jax.vmap(per_image)(gt_boxes, gt_num, is_crowd, im_info,
                                      rngs)
    return {"LocationIndex": [loc_idx], "TargetBBox": [tgt_bbox],
            "BBoxInsideWeight": [in_w], "ScoreIndex": [score_idx],
            "TargetLabel": [labels], "ScoreWeight": [score_w],
            "ForegroundNumber": [fg_counts]}


@register_op("generate_proposal_labels")
def generate_proposal_labels(ctx, ins, attrs):
    """Fast-RCNN head sampling: proposals + gts → sampled rois with
    class labels and per-class regression targets (reference
    detection/generate_proposal_labels_op.cc).

    inputs: RpnRois (N, R, 4) + RpnRoisNum (N,) (generate_proposals
    contract), GtClasses (N, G), IsCrowd (N, G), GtBoxes (N, G, 4),
    GtNum (N,), ImInfo (N, 3).
    outputs (B = batch_size_per_im slots, fg compacted first):
      Rois (N, B, 4) image-scale rois; LabelsInt32 (N, B) (bg 0, padded
      slots -1); BboxTargets (N, B, 4C); BboxInsideWeights /
      BboxOutsideWeights (N, B, 4C); RoisNum (N,) active counts."""
    rois_in = first(ins, "RpnRois").astype(jnp.float32)
    rois_num = opt_in(ins, "RpnRoisNum")
    gt_classes = first(ins, "GtClasses")
    is_crowd = opt_in(ins, "IsCrowd")
    gt_boxes = first(ins, "GtBoxes").astype(jnp.float32)
    gt_num = opt_in(ins, "GtNum")
    im_info = first(ins, "ImInfo").astype(jnp.float32)

    b_total = int(attrs.get("batch_size_per_im", 256))
    fg_frac = float(attrs.get("fg_fraction", 0.25))
    fg_thresh = float(attrs.get("fg_thresh", 0.5))
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(attrs.get("bg_thresh_lo", 0.0))
    reg_w = [float(v) for v in attrs.get("bbox_reg_weights",
                                         [0.1, 0.1, 0.2, 0.2])]
    class_nums = int(attrs.get("class_nums", 81))
    use_random = bool(attrs.get("use_random", True))
    f_total = int(b_total * fg_frac)

    n, r = rois_in.shape[0], rois_in.shape[1]
    g = gt_boxes.shape[1]
    k = g + r
    if rois_num is None:
        rois_num = jnp.full((n,), r, jnp.int32)
    if gt_num is None:
        gt_num = jnp.full((n,), g, jnp.int32)
    if is_crowd is None:
        is_crowd = jnp.zeros((n, g), jnp.int32)
    rngs = jax.random.split(ctx.rng(), n * 2).reshape(n, 2, 2)

    def per_image(props, pnum, gcls, crowd, gts, gnum, info, rng2):
        im_scale = jnp.maximum(info[2], 1e-6)
        props = props / im_scale
        # candidate pool: gts first (reference Concat(gt, rois)), crowd
        # gts kept as rows but disqualified below
        boxes = jnp.concatenate([gts, props], axis=0)       # (K, 4)
        gt_valid_col = jnp.arange(g) < gnum
        row_valid = jnp.concatenate(
            [gt_valid_col,
             jnp.arange(r) < pnum])
        iou = _pixel_iou(boxes, gts)
        iou = jnp.where(gt_valid_col[None, :], iou, -1.0)
        # crowd gts stay as COLUMNS like the reference (it computes
        # BboxOverlaps(boxes, raw_gt) with no crowd column filter,
        # generate_proposal_labels_op.cc:246-250 — only crowd ROWS are
        # disqualified, :126-128); real IoUs are >= 0, so a -1 max means
        # "no valid gt at all" → every proposal is background (the
        # annotation-free-image case), not "no sample"
        max_ov = jnp.max(iou, axis=1)
        gt_arg = jnp.argmax(iou, axis=1)
        max_ov = jnp.maximum(max_ov, 0.0)
        is_crowd_row = jnp.concatenate(
            [(crowd != 0) & gt_valid_col, jnp.zeros((r,), jnp.bool_)])
        max_ov = jnp.where(is_crowd_row, -1.0, max_ov)
        max_ov = jnp.where(row_valid, max_ov, -2.0)

        fg_cand = max_ov > fg_thresh
        fg_sel, fg_cnt = _sample_budget(fg_cand, f_total, rng2[0],
                                        use_random)
        bg_cand = (max_ov >= bg_lo) & (max_ov < bg_hi)
        bg_sel, bg_cnt = _sample_budget(bg_cand, b_total - fg_cnt,
                                        rng2[1], use_random)

        order, count = _compact([(fg_sel, 0.0), (bg_sel, 1.0)], b_total)
        slot = jnp.arange(b_total)
        active = slot < count
        is_fg = slot < fg_cnt
        sampled = boxes[order]                              # (B, 4)
        gt_for = gts[gt_arg[order]]
        labels = jnp.where(
            is_fg, gt_classes_row(gcls, gt_arg[order]),
            jnp.where(active, 0, -1))
        deltas = _box_to_delta(sampled, gt_for, reg_w)
        deltas = jnp.where(is_fg[:, None], deltas, 0.0)
        # expand to per-class slots: row i writes its 4 targets at
        # columns 4*label .. 4*label+3 (fg only)
        cls_ids = jnp.clip(labels, 0, class_nums - 1)
        col = jax.nn.one_hot(cls_ids, class_nums,
                             dtype=jnp.float32)             # (B, C)
        expanded = (col[:, :, None] * deltas[:, None, :]).reshape(
            b_total, 4 * class_nums)
        w = jnp.where(is_fg[:, None], 1.0,
                      jnp.zeros((b_total, 1))) * col[:, :, None].reshape(
            b_total, class_nums, 1).repeat(4, axis=2).reshape(
            b_total, 4 * class_nums)
        rois_out = jnp.where(active[:, None], sampled * im_scale, 0.0)
        return (rois_out, labels.astype(jnp.int32),
                jnp.where(is_fg[:, None], expanded, 0.0),
                w, w, count)

    def gt_classes_row(gcls, idx):
        return gcls[idx].astype(jnp.int32)

    (rois, labels, tgts, in_w, out_w, counts) = jax.vmap(per_image)(
        rois_in, rois_num, gt_classes, is_crowd, gt_boxes, gt_num,
        im_info, rngs)
    return {"Rois": [rois], "LabelsInt32": [labels],
            "BboxTargets": [tgts], "BboxInsideWeights": [in_w],
            "BboxOutsideWeights": [out_w], "RoisNum": [counts]}


@register_op("mine_hard_examples")
def mine_hard_examples(ctx, ins, attrs):
    """Hard-negative mining for SSD-style training (reference
    detection/mine_hard_examples_op.cc): per image, select the
    highest-loss eligible negatives — min(neg_pos_ratio * positives,
    eligible) for max_negative, min(sample_size, eligible) for
    hard_example.

    Static contract: NegIndices (N, P) ascending indices padded with
    -1, plus NegMask (N, P) 0/1 (divergence: replaces the LoD list) and
    UpdatedMatchIndices (N, P)."""
    cls_loss = first(ins, "ClsLoss").astype(jnp.float32)
    loc_loss = opt_in(ins, "LocLoss")
    match_idx = first(ins, "MatchIndices")
    match_dist = first(ins, "MatchDist").astype(jnp.float32)
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    thresh = float(attrs.get("neg_dist_threshold", 0.5))
    sample_size = int(attrs.get("sample_size", 0))
    mtype = attrs.get("mining_type", "max_negative")
    if mtype not in ("max_negative", "hard_example"):
        raise ValueError(f"unknown mining_type {mtype!r}")

    n, p = cls_loss.shape
    loss = cls_loss
    if mtype == "hard_example" and loc_loss is not None:
        loss = cls_loss + loc_loss.astype(jnp.float32)

    if mtype == "max_negative":
        eligible = (match_idx == -1) & (match_dist < thresh)
        num_pos = jnp.sum((match_idx != -1).astype(jnp.int32), axis=1)
        budget = (num_pos.astype(jnp.float32) * ratio).astype(jnp.int32)
    else:
        eligible = jnp.ones((n, p), jnp.bool_)
        budget = jnp.full((n,), sample_size, jnp.int32)

    masked = jnp.where(eligible, loss, -jnp.inf)
    order = jnp.argsort(-masked, axis=1)
    rank = jnp.zeros((n, p), jnp.int32)
    rank = jax.vmap(
        lambda rk, o: rk.at[o].set(jnp.arange(p, dtype=jnp.int32)))(
        rank, order)
    count = jnp.minimum(jnp.sum(eligible.astype(jnp.int32), axis=1),
                        budget)
    selected = eligible & (rank < count[:, None])

    if mtype == "hard_example":
        neg_sel = selected & (match_idx == -1)
        updated = jnp.where((match_idx > -1) & ~selected, -1, match_idx)
    else:
        neg_sel = selected
        updated = match_idx

    # ascending compaction, pad -1 (reference emits a std::set per image)
    key = jnp.where(neg_sel, jnp.arange(p, dtype=jnp.float32)[None, :],
                    jnp.inf)
    neg_order = jnp.argsort(key, axis=1)
    neg_count = jnp.sum(neg_sel.astype(jnp.int32), axis=1)
    neg_idx = jnp.where(jnp.arange(p)[None, :] < neg_count[:, None],
                        neg_order, -1).astype(jnp.int32)
    return {"NegIndices": [neg_idx],
            "NegMask": [neg_sel.astype(jnp.float32)],
            "UpdatedMatchIndices": [updated]}


@register_op("detection_map")
def detection_map(ctx, ins, attrs):
    """In-graph mean Average Precision (reference:
    operators/detection_map_op.cc — 11point / integral AP per SSD eval).

    Padded-dense redesign of the reference's LoD contract: DetectRes is
    (N, M, 6) rows [label, score, xmin, ymin, xmax, ymax] (label < 0 =
    padding), Label is (N, G, 6) rows [label, xmin, ymin, xmax, ymax,
    difficult] (or 5 cols = no difficult flags).  Matching follows the
    reference: per image/class, detections in descending score order
    are each assigned their highest-IoU gt (match iff strictly IoU >
    overlap_threshold); a det whose gt was already claimed is an FP,
    and a difficult-gt match is ignored when evaluate_difficult is
    false.  The reference op's cross-batch accumulation state
    (PosCount/TruePos/FalsePos) is deliberately NOT in-graph — state
    lives host-side in metrics.DetectionMAP, keeping the op pure for
    jit (divergence documented, SURVEY.md §5.7 segment style).
    Output MAP is a scalar fraction."""
    det = first(ins, "DetectRes")
    gt = first(ins, "Label")
    class_num = int(attrs["class_num"])
    bg = attrs.get("background_label", 0)
    thr = attrs.get("overlap_threshold", 0.3)
    eval_difficult = attrs.get("evaluate_difficult", True)
    ap_type = attrs.get("ap_type", "integral")

    n, m, _ = det.shape
    g = gt.shape[1]
    det_lbl = det[:, :, 0].astype(jnp.int32)
    det_score = det[:, :, 1]
    # reference ClipBBox (detection_map_op.h:152): detections clamp to
    # the normalized [0, 1] frame before IoU
    det_box = jnp.clip(det[:, :, 2:6], 0.0, 1.0)
    gt_lbl = gt[:, :, 0].astype(jnp.int32)
    gt_box = gt[:, :, 1:5]
    gt_diff = (gt[:, :, 5] > 0.5 if gt.shape[2] > 5
               else jnp.zeros((n, g), bool))
    det_valid = det_lbl >= 0
    gt_valid = gt_lbl >= 0

    def per_image(dl, ds, db, gl, gb, gd, dv, gv):
        iou = _iou_matrix(db, gb)  # (M, G)
        order = jnp.argsort(-jnp.where(dv, ds, -jnp.inf))

        # reference loop (detection_map_op.h:378-414): each detection is
        # assigned to its max-overlap same-class gt REGARDLESS of
        # visited state; if max_overlap > thr (strict) and that gt was
        # already claimed by a higher-scored det, the det is a plain FP.
        # A difficult gt match with evaluate_difficult=False contributes
        # neither tp nor fp and does not mark the gt visited.
        def step(visited, di):
            same = (gl == dl[di]) & gv
            iou_i = jnp.where(same, iou[di], -1.0)
            j = jnp.argmax(iou_i)
            hit = (iou_i[j] > thr) & dv[di]
            med = bool(eval_difficult) | ~gd[j]
            tp = hit & med & ~visited[j]
            fp = dv[di] & (~hit | (hit & med & visited[j]))
            return visited | jnp.zeros_like(visited).at[j].set(tp), \
                (di, tp, fp)

        _, (idx, tp, fp) = lax.scan(step, jnp.zeros((g,), bool), order)
        # scatter flags back to original det positions
        tp_o = jnp.zeros((m,), bool).at[idx].set(tp)
        fp_o = jnp.zeros((m,), bool).at[idx].set(fp)
        return tp_o, fp_o

    tp, fp = jax.vmap(per_image)(det_lbl, det_score, det_box, gt_lbl,
                                 gt_box, gt_diff, det_valid, gt_valid)

    # per-class AP over the flattened batch
    flat_lbl = det_lbl.reshape(-1)
    flat_score = det_score.reshape(-1)
    flat_tp = tp.reshape(-1)
    flat_fp = fp.reshape(-1)
    order = jnp.argsort(-flat_score)
    flat_lbl, flat_tp, flat_fp = (flat_lbl[order], flat_tp[order],
                                  flat_fp[order])

    counts_gt = gt_lbl.reshape(-1)
    counts_diff = gt_diff.reshape(-1)
    counts_valid = gt_valid.reshape(-1)

    def class_ap(c):
        npos = jnp.sum(counts_valid & (counts_gt == c)
                       & (eval_difficult | ~counts_diff))
        # only counted dets of this class (ignored difficult-matches
        # have tp=fp=False and drop out of precision's denominator,
        # matching the reference's unrecorded pairs)
        mine = (flat_lbl == c) & (flat_tp | flat_fp)
        ctp = jnp.cumsum(jnp.where(mine, flat_tp, 0))
        cfp = jnp.cumsum(jnp.where(mine, flat_fp, 0))
        denom = jnp.maximum(ctp + cfp, 1)
        prec = ctp / denom
        rec = ctp / jnp.maximum(npos, 1)
        if ap_type == "11point":
            pts = jnp.linspace(0.0, 1.0, 11)
            pmax = jnp.max(
                jnp.where(mine[None, :] & (rec[None, :] >= pts[:, None]),
                          prec[None, :], 0.0), axis=1)
            ap = jnp.mean(pmax)
        else:
            # integral: precision * delta-recall summed — delta-recall
            # is 1/npos exactly at each new TP (detection_map_op.h:459)
            new_tp = jnp.where(mine, flat_tp, False)
            ap = jnp.sum(jnp.where(new_tp, prec, 0.0)) / jnp.maximum(
                npos, 1)
        # the reference averages over classes that have BOTH gt
        # positives and at least one recorded detection
        # (detection_map_op.h:423-427; its `label_num_pos ==
        # background_label` count-vs-id comparison is a quirk we do not
        # replicate beyond its bg=0 no-op effect)
        return ap, (npos > 0) & jnp.any(mine)

    classes = jnp.array([c for c in range(class_num) if c != bg],
                        dtype=jnp.int32)
    aps, has = jax.vmap(class_ap)(classes)
    n_eval = jnp.maximum(jnp.sum(has), 1)
    mean_ap = jnp.sum(jnp.where(has, aps, 0.0)) / n_eval
    return {"MAP": [mean_ap]}


@register_op("polygon_box_transform")
def polygon_box_transform(ctx, ins, attrs):
    """EAST-style geometry decode (reference
    detection/polygon_box_transform_op.cc): input (N, 2n, H, W) holds
    per-pixel offsets to n polygon corners; even channels decode as
    4*x_pixel - offset, odd channels as 4*y_pixel - offset (the
    reference's quad geometry maps run at 1/4 resolution)."""
    x = first(ins, "Input")
    n, c, h, w = x.shape
    xs = jnp.arange(w, dtype=jnp.float32)[None, None, None, :] * 4.0
    ys = jnp.arange(h, dtype=jnp.float32)[None, None, :, None] * 4.0
    even = (jnp.arange(c) % 2 == 0).reshape(1, c, 1, 1)
    o = jnp.where(even, xs - x.astype(jnp.float32),
                  ys - x.astype(jnp.float32))
    return {"Output": [o.astype(x.dtype)]}


@register_op("roi_perspective_transform")
def roi_perspective_transform(ctx, ins, attrs):
    """Perspective-warp quadrilateral ROIs to a fixed grid (reference
    detection/roi_perspective_transform_op.cc, OCR text rectification):
    each ROI is 4 corners (x0,y0..x3,y3); a homography maps the output
    grid back into the input, sampled bilinearly, zero outside the quad.

    ROIs are (R, 9): [batch_idx, x0, y0, x1, y1, x2, y2, x3, y3]
    (batch-in-box replaces the reference's LoD mapping)."""
    x = first(ins, "X")
    rois = first(ins, "ROIs").astype(jnp.float32)
    th = int(attrs["transformed_height"])
    tw = int(attrs["transformed_width"])
    scale = float(attrs.get("spatial_scale", 1.0))
    _n, c, ih, iw = x.shape
    bix = rois[:, 0].astype(jnp.int32)
    quad = rois[:, 1:].reshape(-1, 4, 2) * scale   # (R, 4, [x, y])

    def transform_matrix(rx, ry):
        # reference get_transform_matrix: estimated quad size fixes the
        # normalized grid; the homography maps (out_w, out_h, 1) to
        # input coords
        len1 = jnp.hypot(rx[0] - rx[1], ry[0] - ry[1])
        len2 = jnp.hypot(rx[1] - rx[2], ry[1] - ry[2])
        len3 = jnp.hypot(rx[2] - rx[3], ry[2] - ry[3])
        len4 = jnp.hypot(rx[3] - rx[0], ry[3] - ry[0])
        est_h = jnp.maximum((len2 + len4) / 2.0, 1e-6)
        est_w = jnp.maximum((len1 + len3) / 2.0, 1e-6)
        norm_h = float(th)
        norm_w = jnp.minimum(
            jnp.round(est_w * (norm_h - 1) / est_h) + 1, float(tw))
        nw1 = jnp.maximum(norm_w - 1.0, 1e-6)
        nh1 = float(th - 1) if th > 1 else 1e-6
        dx1, dx2 = rx[1] - rx[2], rx[3] - rx[2]
        dx3 = rx[0] - rx[1] + rx[2] - rx[3]
        dy1, dy2 = ry[1] - ry[2], ry[3] - ry[2]
        dy3 = ry[0] - ry[1] + ry[2] - ry[3]
        den = dx1 * dy2 - dx2 * dy1
        den = jnp.where(jnp.abs(den) < 1e-9, 1e-9, den)
        a31 = (dx3 * dy2 - dx2 * dy3) / den / nw1
        a32 = (dx1 * dy3 - dx3 * dy1) / den / nh1
        a11 = (rx[1] - rx[0] + a31 * nw1 * rx[1]) / nw1
        a12 = (rx[3] - rx[0] + a32 * nh1 * rx[3]) / nh1
        a21 = (ry[1] - ry[0] + a31 * nw1 * ry[1]) / nw1
        a22 = (ry[3] - ry[0] + a32 * nh1 * ry[3]) / nh1
        return jnp.array([[a11, a12, rx[0]],
                          [a21, a22, ry[0]],
                          [a31, a32, 1.0]])

    def in_quad(px, py, rx, ry):
        # point-in-quad via consistent edge cross-product signs
        crosses = []
        for k in range(4):
            x1, y1 = rx[k], ry[k]
            x2, y2 = rx[(k + 1) % 4], ry[(k + 1) % 4]
            crosses.append((x2 - x1) * (py - y1) - (y2 - y1) * (px - x1))
        cr = jnp.stack(crosses)
        eps = 1e-4
        inside = (jnp.all(cr >= -eps, axis=0) |
                  jnp.all(cr <= eps, axis=0))
        return inside

    def one(bi, q):
        rx, ry = q[:, 0], q[:, 1]
        m = transform_matrix(rx, ry)
        ow = jnp.arange(tw, dtype=jnp.float32)[None, :]
        oh = jnp.arange(th, dtype=jnp.float32)[:, None]
        u = m[0, 0] * ow + m[0, 1] * oh + m[0, 2]
        v = m[1, 0] * ow + m[1, 1] * oh + m[1, 2]
        wgt = m[2, 0] * ow + m[2, 1] * oh + m[2, 2]
        wgt = jnp.where(jnp.abs(wgt) < 1e-9, 1e-9, wgt)
        src_x = u / wgt
        src_y = v / wgt
        valid = (in_quad(src_x, src_y, rx, ry)
                 & (src_x >= -0.5) & (src_x <= iw - 0.5)
                 & (src_y >= -0.5) & (src_y <= ih - 0.5))
        sx = jnp.clip(src_x, 0.0, iw - 1.0)
        sy = jnp.clip(src_y, 0.0, ih - 1.0)
        x0 = jnp.floor(sx).astype(jnp.int32)
        y0 = jnp.floor(sy).astype(jnp.int32)
        x1 = jnp.minimum(x0 + 1, iw - 1)
        y1 = jnp.minimum(y0 + 1, ih - 1)
        lx = sx - x0
        ly = sy - y0
        fm = x[bi]                                    # (C, H, W)
        val = (fm[:, y0, x0] * (1 - ly) * (1 - lx)
               + fm[:, y1, x0] * ly * (1 - lx)
               + fm[:, y0, x1] * (1 - ly) * lx
               + fm[:, y1, x1] * ly * lx)             # (C, th, tw)
        return jnp.where(valid[None], val, 0.0)

    o = jax.vmap(one)(bix, quad)
    return {"Out": [o.astype(x.dtype)]}
