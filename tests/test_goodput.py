"""Observe pillar 8: the wall-clock goodput ledger.

Locks in the ISSUE 16 acceptance criteria:
- Σ categories == elapsed wall, by construction ("idle" is the
  residual) — fake-clock exact and real-Trainer within rounding,
- the guard discipline: threading a ledger adds zero dispatches, zero
  retraces, and the step lowering is byte-identical with or without
  it (the ledger is PURE HOST — monotonic reads at phase boundaries),
- XLA compile wall is re-attributed out of whichever phase it struck
  (a first step contributes dispatch time to "step", compile to
  "compile"),
- restart-replay badput: a crash between the last checkpoint and the
  progress cursor makes the relaunch re-execute steps, counted as
  "replay" with the resume→crash window recorded,
- data stalls: a slow reader's next() time lands in "data_stall",
- checkpoint blocking lands in "checkpoint" and ckpt_stats keeps the
  old blocking_ms/write_ms keys as ledger reads,
- prometheus exposition via goodput_collector in the Trainer's
  MetricsRegistry,
- the step-anatomy chrome trace: one row per category under pid 1000.
"""

import contextlib
import json
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observe
from paddle_tpu.observe.goodput import (CATEGORIES, GOODPUT_TRACE_PID,
                                        PHASE_CATEGORIES, GoodputLedger,
                                        format_goodput_table,
                                        goodput_report)


class FakeClock:
    """Deterministic monotonic clock for exact-arithmetic tests."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


# ---------------------------------------------------------------------------
# Ledger unit tests (fake clock: exact arithmetic)
# ---------------------------------------------------------------------------

def test_sum_of_categories_equals_wall_exactly():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.open_window()
    with led.phase("step", steps=1):
        clk.advance(1.0)
    with led.phase("data_stall"):
        clk.advance(0.5)
    with led.phase("checkpoint", label="save:0"):
        clk.advance(0.2)
    clk.advance(0.3)  # unclaimed host time -> idle residual
    led.close_window()
    rep = led.report()
    assert rep["wall_s"] == 2.0
    cats = rep["categories_s"]
    assert set(cats) == set(CATEGORIES)
    assert cats["step"] == 1.0
    assert cats["data_stall"] == 0.5
    assert cats["checkpoint"] == 0.2
    assert cats["idle"] == 0.3
    assert sum(cats.values()) == rep["wall_s"]
    assert abs(sum(rep["fractions"].values()) - 1.0) < 1e-9
    assert rep["goodput"] == 0.5
    assert rep["steps"] == 1
    assert rep["mean_step_s"] == 1.0
    # module-level alias returns the same decomposition
    assert goodput_report(led) == rep


def test_unknown_category_raises():
    led = GoodputLedger(clock=FakeClock())
    with pytest.raises(ValueError, match="unknown goodput category"):
        with led.phase("espresso"):
            pass
    # "idle" is the residual, never claimable explicitly
    with pytest.raises(ValueError):
        with led.phase("idle"):
            pass


def test_nested_phase_own_time_excludes_child():
    """Exclusivity under nesting: a checkpoint inside a step claims
    its slice ONCE — the parent's own time excludes the child's."""
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    with led.window():
        with led.phase("step", steps=1):
            clk.advance(0.4)
            with led.phase("checkpoint"):
                clk.advance(0.3)
            clk.advance(0.3)
    rep = led.report()
    assert rep["categories_s"]["step"] == pytest.approx(0.7)
    assert rep["categories_s"]["checkpoint"] == pytest.approx(0.3)
    assert sum(rep["categories_s"].values()) == \
        pytest.approx(rep["wall_s"])


def test_outside_window_phase_joins_wall():
    """An instrumented wait AFTER close_window (the gang
    done-rendezvous) still keeps Σ categories == wall."""
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.open_window()
    with led.phase("step", steps=1):
        clk.advance(1.0)
    led.close_window()
    with led.phase("barrier_wait"):
        clk.advance(0.7)
    rep = led.report()
    assert rep["wall_s"] == pytest.approx(1.7)
    assert rep["categories_s"]["barrier_wait"] == pytest.approx(0.7)
    assert sum(rep["categories_s"].values()) == \
        pytest.approx(rep["wall_s"])


def test_background_channel_is_not_a_wall_category():
    """Overlapped work (the async checkpoint writer thread) rides the
    side channel — never double-counted into the wall."""
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    with led.window():
        with led.phase("step", steps=1):
            clk.advance(1.0)
        led.note_background("ckpt_write", 1.5)
    rep = led.report()
    assert rep["wall_s"] == 1.0
    assert sum(rep["categories_s"].values()) == rep["wall_s"]
    assert rep["background_ms"] == {"ckpt_write": 1500.0}
    assert led.background_ms("ckpt_write") == 1500.0


def test_open_window_idempotent_and_live_wall():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.open_window()
    clk.advance(1.0)
    led.open_window()  # idempotent: must NOT reset the wall origin
    clk.advance(1.0)
    assert led.wall_s() == pytest.approx(2.0)  # live read, still open
    led.close_window()
    led.close_window()  # idempotent too
    assert led.wall_s() == pytest.approx(2.0)


def test_replay_counting_and_info():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.note_replay((0, 6), (0, 9))
    with led.window():
        with led.phase("replay", steps=3):
            clk.advance(0.9)
        with led.phase("step", steps=2):
            clk.advance(0.8)
    rep = led.report()
    assert rep["replay_steps"] == 3
    assert rep["steps"] == 2
    assert rep["replay"] == {"from": [0, 6], "to": [0, 9]}
    assert rep["categories_s"]["replay"] == pytest.approx(0.9)
    # replay is badput: goodput counts only the fresh steps
    assert rep["goodput"] == pytest.approx(0.8 / 1.7)


def test_effective_mfu_and_straggler_estimate():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    with led.window():
        with led.phase("step", steps=4):
            clk.advance(2.0)
        clk.advance(2.0)
    rep = led.report(mfu=0.32, skew={"max_lag_steps": 4})
    assert rep["goodput"] == 0.5
    assert rep["mfu"] == 0.32
    assert rep["effective_mfu"] == round(0.32 * 0.5, 6)
    assert rep["straggler_est_s"] == pytest.approx(4 * 0.5)
    table = format_goodput_table(rep)
    assert "effective_mfu" in table and "straggler_est_s" in table
    for c in CATEGORIES:
        assert c in table


def test_span_ring_bounded_with_drop_counter():
    clk = FakeClock()
    led = GoodputLedger(clock=clk, max_spans=1)  # clamps to 16
    with led.window():
        for _ in range(20):
            with led.phase("step", steps=1):
                clk.advance(0.01)
    assert led.spans_dropped == 4
    rep = led.report()
    assert rep["spans_dropped"] == 4
    assert rep["steps"] == 20  # counters are NOT ring-bounded


def test_category_s_idle_residual_read():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    with led.window():
        with led.phase("step", steps=1):
            clk.advance(1.0)
        clk.advance(0.25)
    assert led.category_s("idle") == pytest.approx(0.25)
    assert led.category_ms("step") == pytest.approx(1000.0)


# ---------------------------------------------------------------------------
# Chrome trace (the step-anatomy timeline)
# ---------------------------------------------------------------------------

def test_chrome_trace_rows_and_pid(tmp_path):
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    with led.window():
        with led.phase("step", label="s0", steps=1):
            clk.advance(0.5)
        with led.phase("data_stall"):
            clk.advance(0.25)
    path = str(tmp_path / "goodput_trace.json")
    out = led.export_chrome_trace(path)
    with open(path) as f:
        assert json.load(f) == out
    ev = out["traceEvents"]
    procs = [e for e in ev if e["ph"] == "M"
             and e["name"] == "process_name"]
    assert procs[0]["pid"] == GOODPUT_TRACE_PID
    assert procs[0]["args"]["name"] == "training goodput"
    tids = {e["args"]["name"]: e["tid"] for e in ev
            if e["ph"] == "M" and e["name"] == "thread_name"}
    # one thread row per category present, tid = category index
    assert tids == {"step": PHASE_CATEGORIES.index("step"),
                    "data_stall": PHASE_CATEGORIES.index("data_stall")}
    xs = [e for e in ev if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["s0", "data_stall"]
    assert xs[0]["ts"] == 0.0 and xs[0]["dur"] == pytest.approx(5e5)
    assert xs[1]["ts"] == pytest.approx(5e5)
    assert all(e["pid"] == GOODPUT_TRACE_PID for e in xs)
    assert xs[0]["args"]["category"] == "step"
    # an explicit base shifts timestamps — the reqtrace-alignment knob
    shifted = led.export_chrome_trace(base=-1.0)
    xs2 = [e for e in shifted["traceEvents"] if e["ph"] == "X"]
    assert xs2[0]["ts"] == pytest.approx(1e6)


# ---------------------------------------------------------------------------
# Compile re-attribution (real executor, real clock)
# ---------------------------------------------------------------------------

def _named_program(lr=0.1):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        x = layers.data(name="x", shape=[8], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(learning_rate=lr).minimize(loss)
    return main, startup, scope, loss


def _feed(rng, n=8):
    return {"x": rng.rand(n, 8).astype(np.float32),
            "y": rng.rand(n, 1).astype(np.float32)}


def test_compile_reattributed_out_of_step_phase():
    """A first step that triggers XLA compile must NOT inflate "step":
    the compile wall moves to "compile" wherever it struck."""
    main, startup, scope, loss = _named_program()
    feed = _feed(np.random.RandomState(0))
    led = GoodputLedger()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        with led.window():
            with led.phase("step", steps=1):  # first run: compiles
                exe.run(main, feed=feed, fetch_list=[loss])
            with led.phase("step", steps=1):  # warm: dispatch only
                exe.run(main, feed=feed, fetch_list=[loss])
    rep = led.report()
    assert rep["categories_s"]["compile"] > 0.0
    assert rep["steps"] == 2
    # the warm step bounds what a dispatch costs; the cold step's
    # "step" share must be dispatch-sized, not compile-sized
    assert rep["categories_s"]["step"] < rep["wall_s"]
    assert sum(rep["categories_s"].values()) == \
        pytest.approx(rep["wall_s"], abs=1e-3)


def test_window_level_compile_outside_phases():
    """Compile striking inside the window but outside any phase (an
    unwrapped eager warmup) is attributed at close_window."""
    main, startup, scope, loss = _named_program()
    feed = _feed(np.random.RandomState(1))
    led = GoodputLedger()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        led.open_window()
        exe.run(main, feed=feed, fetch_list=[loss])  # no phase
        led.close_window()
    rep = led.report()
    assert rep["categories_s"]["compile"] > 0.0
    assert sum(rep["categories_s"].values()) == \
        pytest.approx(rep["wall_s"], abs=1e-3)


# ---------------------------------------------------------------------------
# Guard discipline: zero overhead, byte-identical lowering
# ---------------------------------------------------------------------------

def test_ledger_is_zero_overhead_and_lowering_identical():
    """The ISSUE 4 guard discipline applied to pillar 8: running under
    a ledger adds zero dispatches and zero retraces, and the step
    lowering is BYTE-IDENTICAL with or without one — the ledger never
    touches the program, the trace, or the device."""
    rng_feed = _feed(np.random.RandomState(0))

    def run_and_count(with_ledger):
        main, startup, scope, loss = _named_program()
        led = GoodputLedger() if with_ledger else None
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            if led is not None:
                led.open_window()
            snap = observe.runtime_stats.snapshot()
            for _ in range(3):
                cm = (led.phase("step", steps=1) if led is not None
                      else contextlib.nullcontext())
                with cm:
                    exe.run(main, feed=rng_feed, fetch_list=[loss])
            delta = observe.runtime_stats.delta(snap)
            if led is not None:
                led.close_window()
            fn, state, feeds = exe._prepare(
                main, rng_feed, [loss.name], scope, 1, True)
            text = fn.lower(state, feeds).as_text()
        return delta, text

    off, text_off = run_and_count(False)
    on, text_on = run_and_count(True)
    assert on["dispatches"] == off["dispatches"]
    assert on["retraces"] == off["retraces"] == 0
    assert "callback" not in text_on  # pure host: no round-trips
    assert text_on == text_off  # byte-identical step lowering


# ---------------------------------------------------------------------------
# Trainer integration (slow reader, checkpoint, replay, metrics)
# ---------------------------------------------------------------------------

def _train_func():
    x = layers.data(name="x", shape=[6], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, size=8, act="relu")
    pred = layers.fc(h, size=1)
    return layers.mean(layers.square_error_cost(pred, y))


def _opt_func():
    return fluid.optimizer.SGDOptimizer(learning_rate=0.01)


def _reader(n=6, delay=0.0):
    def read():
        r = np.random.RandomState(7)
        for _ in range(n):
            if delay:
                time.sleep(delay)
            yield {"x": r.rand(8, 6).astype(np.float32),
                   "y": r.rand(8, 1).astype(np.float32)}

    return read


def _trainer(ckpt_dir, log=None, step_interval=3):
    from paddle_tpu.contrib import CheckpointConfig, Trainer

    tel = (observe.TelemetryConfig(interval=100, log_path=log)
           if log else None)
    return Trainer(_train_func, _opt_func,
                   checkpoint_config=CheckpointConfig(
                       ckpt_dir, step_interval=step_interval,
                       epoch_interval=10 ** 6),
                   telemetry=tel)


def test_trainer_ledger_sums_to_wall_with_data_stall(tmp_path):
    """The run_ci goodput smoke, pinned: a slow reader's sleeps land
    in data_stall, checkpoint blocking in checkpoint, Σ == wall, and
    ckpt_stats keeps the old keys as ledger reads."""
    log = str(tmp_path / "ev.jsonl")
    t = _trainer(str(tmp_path / "ck"), log=log)
    t.train(num_epochs=1, reader=_reader(6, delay=0.02))
    t.stop()
    rep = t.goodput(mfu=0.3254)
    cats = rep["categories_s"]
    assert set(cats) == set(CATEGORIES)
    assert abs(sum(cats.values()) - rep["wall_s"]) < 1e-3
    assert abs(sum(rep["fractions"].values()) - 1.0) < 1e-4
    assert rep["steps"] == 6
    assert rep["replay_steps"] == 0
    assert cats["data_stall"] >= 6 * 0.02 * 0.8  # the sleeps, found
    assert cats["checkpoint"] > 0.0  # 2 saves @ interval 3
    # effective_mfu is derived from the UNROUNDED step fraction inside
    # report(); recomputing from the rounded goodput can differ by 1e-6
    assert rep["effective_mfu"] == \
        pytest.approx(0.3254 * rep["goodput"], abs=2e-6)
    # satellite: the pre-pillar-8 checkpoint-cost keys are now READS
    # of the ledger — old consumers see identical semantics
    assert t.ckpt_stats["blocking_ms"] == pytest.approx(
        t.goodput_ledger.category_ms("checkpoint"), abs=1e-3)
    assert t.ckpt_stats["write_ms"] == pytest.approx(
        t.goodput_ledger.background_ms("ckpt_write"), abs=1e-3)
    # the event log carries the report + the train_end summary fields
    events = observe.read_events(log)
    kinds = [e["event"] for e in events]
    assert "goodput_report" in kinds
    end = [e for e in events if e["event"] == "train_end"][-1]
    for k in ("goodput", "replay_steps", "wall_s",
              "ckpt_blocking_ms", "ckpt_write_ms"):
        assert k in end, k
    gp = [e for e in events if e["event"] == "goodput_report"][-1]
    assert gp["goodput"] == end["goodput"]


def test_trainer_restart_replay_badput(tmp_path):
    """ISSUE 16 acceptance (in-process form): crash after step 6's
    progress write but before step 7's, resume from the step-6
    checkpoint -> exactly the steps between checkpoint and crash
    cursor are accounted as replay, and replay seconds track
    replay_steps x mean step time."""
    from paddle_tpu.contrib.trainer import EndStepEvent

    ck = str(tmp_path / "ck")
    t = _trainer(ck)

    class Boom(RuntimeError):
        pass

    def handler(e):
        # EndStepEvent fires BEFORE the progress write for its step:
        # raising at step 7 leaves the crash cursor at (0, 7)
        if isinstance(e, EndStepEvent) and e.step == 7:
            raise Boom("chaos")

    with pytest.raises(Boom):
        t.train(num_epochs=1, reader=_reader(12),
                event_handler=handler)
    t.stop()

    t2 = _trainer(ck)
    # saves at steps 3 and 6 (interval 3): resume cursor is (0, 6)
    assert (t2._resume_epoch, t2._resume_step_in_epoch) == (0, 6)
    t2.train(num_epochs=1, reader=_reader(12))
    t2.stop()
    rep = t2.goodput()
    assert rep["replay_steps"] == 1  # step 6 ran twice
    assert rep["steps"] == 5  # steps 7..11 are fresh work
    assert rep["replay"] == {"from": [0, 6], "to": [0, 7]}
    assert rep["categories_s"]["replay"] > 0.0
    # replay badput ~ replayed-step count x mean step time; the first
    # resumed dispatch pays a residual cold cost beyond the
    # re-attributed trace/compile wall — allowed as absolute slack
    est = rep["replay_steps"] * rep["mean_step_s"]
    assert 0.1 * est < rep["categories_s"]["replay"] < 10 * est + 0.1
    assert abs(sum(rep["categories_s"].values()) - rep["wall_s"]) \
        < 1e-3
    # a clean run records no replay
    t3 = _trainer(str(tmp_path / "ck2"))
    t3.train(num_epochs=1, reader=_reader(3))
    t3.stop()
    clean = t3.goodput()
    assert clean["replay_steps"] == 0 and "replay" not in clean


def test_trainer_prometheus_exposition(tmp_path):
    """goodput_collector rides the Trainer's MetricsRegistry: the
    pillar-8 families appear in text exposition format 0.0.4."""
    t = _trainer(str(tmp_path / "ck"))
    t.train(num_epochs=1, reader=_reader(3))
    t.stop()
    text = t.metrics_registry().prometheus_text()
    assert "goodput_available 1" in text
    assert "goodput_fraction_good " in text
    assert "goodput_wall_seconds_total " in text
    assert "goodput_steps_total 3" in text
    assert "goodput_replay_steps_total 0" in text
    assert 'goodput_fraction{category="step"}' in text
    assert 'goodput_badput_seconds_total{category="checkpoint"}' \
        in text
    # "step" is goodput, never badput
    assert 'goodput_badput_seconds_total{category="step"}' not in text
    assert "goodput_mean_step_seconds " in text
    assert "goodput_effective_mfu" in text  # family present (no mfu)


def test_goodput_collector_before_any_ledger():
    """fetch -> None (no run yet) degrades to goodput_available 0 —
    the one-sick-subsystem isolation contract."""
    from paddle_tpu.observe.registry import (MetricsRegistry,
                                             goodput_collector)

    reg = MetricsRegistry().register(
        "goodput", goodput_collector(lambda: None))
    text = reg.prometheus_text()
    assert "goodput_available 0" in text
    assert "goodput_wall_seconds_total" not in text
    assert 'observe_collector_up{collector="goodput"} 1' in text
