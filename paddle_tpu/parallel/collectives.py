"""Functional collectives over the mesh.

Replaces the reference's NCCL op handles and raw nccl ops
(details/all_reduce_op_handle.cc, operators/nccl/nccl_op.cu.cc,
collective_server).  These are thin shard_map wrappers around XLA
collectives (psum / all_gather / ppermute / all_to_all) for code that
wants explicit communication (ring attention, expert dispatch); ordinary
data/tensor parallelism never calls these — GSPMD inserts collectives
from sharding annotations alone.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def compat_shard_map(fn, mesh, in_specs, out_specs, check=False):
    """shard_map with two jax API drifts smoothed over: the import
    location (jax.shard_map vs jax.experimental.shard_map) and the
    replication-check kwarg rename (check_rep -> check_vma).  `check`
    feeds whichever kwarg this jax has."""
    import inspect

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    kw = ("check_vma" if "check_vma" in
          inspect.signature(shard_map).parameters else "check_rep")
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **{kw: check})


def _shard_map(fn, mesh, in_specs, out_specs):
    return compat_shard_map(fn, mesh, in_specs, out_specs)


def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def all_reduce(x, mesh, axis: str, shard_dim: int = 0, op: str = "sum"):
    """Reduce per-device values stacked along `shard_dim` to one
    replicated result with that dim removed (the PE all-reduce,
    details/all_reduce_op_handle.cc: N per-device grads → one summed
    grad everywhere)."""
    spec = [None] * x.ndim
    spec[shard_dim] = axis

    def f(xs):
        if op == "sum":
            r = jax.lax.psum(xs, axis)
        elif op == "max":
            r = jax.lax.pmax(xs, axis)
        elif op == "mean":
            r = jax.lax.pmean(xs, axis)
        else:
            raise ValueError(op)
        return jax.numpy.squeeze(r, shard_dim)

    out_spec = [None] * (x.ndim - 1)
    return _shard_map(f, mesh, (P(*spec),), P(*out_spec))(x)


def all_gather(x, mesh, axis: str, shard_dim: int = 0):
    spec = [None] * x.ndim
    spec[shard_dim] = axis

    def f(xs):
        return jax.lax.all_gather(xs, axis, axis=shard_dim, tiled=True)

    return _shard_map(f, mesh, (P(*spec),), P(*[None] * x.ndim))(x)


def reduce_scatter(x, mesh, axis: str, shard_dim: int = 0):
    """Replicated-in, sharded-out sum (the kReduce build-strategy mode,
    build_strategy.h:55)."""
    def f(xs):
        return jax.lax.psum_scatter(xs, axis, scatter_dimension=shard_dim,
                                    tiled=True)

    out_spec = [None] * x.ndim
    out_spec[shard_dim] = axis
    return _shard_map(f, mesh, (P(*[None] * x.ndim),), P(*out_spec))(x)


def ppermute(x, mesh, axis: str, perm, shard_dim: int = 0):
    """Neighbor exchange over the ring (ICI) — building block for ring
    attention."""
    spec = [None] * x.ndim
    spec[shard_dim] = axis

    def f(xs):
        return jax.lax.ppermute(xs, axis, perm)

    return _shard_map(f, mesh, (P(*spec),), P(*spec))(x)


def all_to_all(x, mesh, axis: str, split_dim: int, concat_dim: int):
    """Ulysses-style head/sequence exchange."""
    n = mesh.shape[axis]
    in_spec = [None] * x.ndim
    in_spec[concat_dim] = axis

    def f(xs):
        return jax.lax.all_to_all(xs, axis, split_axis=split_dim,
                                  concat_axis=concat_dim, tiled=True)

    out_spec = [None] * x.ndim
    out_spec[split_dim] = axis
    return _shard_map(f, mesh, (P(*in_spec),), P(*out_spec))(x)


def barrier(mesh, axis: str):
    """Synchronization barrier (the reference's send_barrier /
    fetch_barrier ops) — a trivial psum forces a cross-replica sync."""
    def f():
        return jax.lax.psum(jnp.ones(()), axis)

    return _shard_map(f, mesh, (), P())()
