"""Tiled flash-attention forward kernel (Pallas, TPU).

Online-softmax attention: never materializes the (Tq, Tk) score matrix in
HBM — q-blocks stream k/v-blocks through VMEM keeping running max /
normalizer / accumulator (the standard flash algorithm).  This is the
modern TPU equivalent of the LoD no-padding efficiency story
(SURVEY.md §5.7): padding positions are masked via an additive key bias.

Forward runs in Pallas; backward is a custom-VJP recompute in plain XLA
using the saved logsumexp (correct, O(Tq*Tk) memory in the backward —
the Pallas backward kernel is a later-round upgrade; ring attention
(parallel/ring_attention.py) is the long-context training path).

Supported bias: additive key-padding bias broadcastable as (N, 1, 1, Tk),
plus in-kernel causal masking.  Richer biases fall back to the XLA
composition in ops/attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Tuned on v5e (seq 2048, d 128): q=256/k=1024 beats the XLA-composed
# attention; both dims are clamped to the actual sequence length.
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                t_k):
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qb = pl.program_id(1)
    # causal: skip k-blocks strictly above the diagonal
    run = (qb + 1) * block_q > kb * block_k if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]                      # (block_q, d)
        k = k_ref[0]                      # (block_k, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)

        # Always mask k-positions past the true sequence length: when
        # t_k % block_k != 0 the last k-block is padded and its garbage
        # columns would otherwise corrupt the online softmax and lse.
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < t_k
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_scr[:]                 # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)            # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)   # (block_q, 1)
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        # Zero padded v-rows: block padding is undefined memory and
        # 0 * NaN would poison the accumulator even though p==0 there.
        v_rows = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)
        vv = jnp.where(v_rows < t_k, v_ref[0], 0)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(vv.dtype), vv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(kb == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # lse replicated over 8 sublanes to satisfy TPU tiling of the
        # (nh, 8, t_q) output layout
        lse = (m_scr[:] + jnp.log(l))[:, 0]
        lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


def _flash_fwd(q, k, v, bias, scale, causal, block_q, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    grid = (nh, pl.cdiv(t_q, block_q), pl.cdiv(t_k, block_k))

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda h, i, j: (h, j, 0)),
    ]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, 1, block_k), lambda h, i, j: (h, 0, 0, j)))
        args.append(bias)
        kern = functools.partial(
            _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, t_k=t_k)
    else:
        def kern(q_ref, k_ref, v_ref, o_ref, lse_ref, m, l, acc):
            _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref, m, l,
                        acc, scale=scale, causal=causal, block_q=block_q,
                        block_k=block_k, t_k=t_k)

    o, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda h, i, j: (h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nh, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((nh, 8, t_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )(*args)
    return o, lse[:, 0, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, bias, scale, causal, block_q, block_k):
    o, _ = _flash_fwd(q, k, v, bias, scale, causal, block_q, block_k)
    return o


def _flash_vjp_fwd(q, k, v, bias, scale, causal, block_q, block_k):
    o, lse = _flash_fwd(q, k, v, bias, scale, causal, block_q, block_k)
    return o, (q, k, v, bias, o, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, bias, o, lse = res
    # Recompute-based backward (standard flash bwd math, XLA-fused):
    # p = exp(s - lse); dv = p^T do; dp = do v^T;
    # ds = p * (dp - rowsum(do*o)); dq = ds k; dk = ds^T q.
    s = jnp.einsum("hqd,hkd->hqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        s = s + bias[:, 0].astype(jnp.float32)
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), jnp.bool_))
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse[..., None])
    do_f = do.astype(jnp.float32)
    dv = jnp.einsum("hqk,hqd->hkd", p, do_f)
    dp = jnp.einsum("hqd,hkd->hqk", do_f, v.astype(jnp.float32))
    delta = jnp.sum(do_f * o.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("hqk,hkd->hqd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("hqk,hqd->hkd", ds, q.astype(jnp.float32)) * scale
    dbias = None
    if bias is not None:
        db = jnp.sum(ds, axis=1)[:, None, None, :]  # sum over q
        dbias = db.astype(bias.dtype)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dbias


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def pallas_flash_attention(q, k, v, bias=None, scale=None, causal=False,
                           block_q=DEFAULT_BLOCK_Q,
                           block_k=DEFAULT_BLOCK_K):
    """q/k/v: (N, H, T, D); bias: None or broadcastable (N, 1, 1, Tk)."""
    n, h, t_q, d = q.shape
    t_k = k.shape[2]
    if scale is None:
        scale = d ** -0.5
    if bias is not None:
        bias = jnp.broadcast_to(bias, (n, 1, 1, t_k))
        bias = jnp.repeat(bias, h, axis=1).reshape(n * h, 1, 1, t_k)

    qf = q.reshape(n * h, t_q, d)
    kf = k.reshape(n * h, t_k, d)
    vf = v.reshape(n * h, t_k, d)
    o = _flash(qf, kf, vf, bias, float(scale), bool(causal),
               int(block_q), int(block_k))
    return o.reshape(n, h, t_q, d)
