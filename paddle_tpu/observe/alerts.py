"""SLO alert engine — observe pillar 9 (the watching half).

Pillars 1–8 made every signal recordable and scrapeable; this module
is the first consumer that *watches* them: declarative rules evaluated
on a background thread over `MetricsRegistry` snapshots.  Everything
here is pure host bookkeeping — the engine only ever calls
`registry.snapshot()` (collectors read existing host-side counters and
histograms), so it performs ZERO device dispatches, installs no
step-path hooks, and the step lowering is byte-identical with the
engine running or absent (pinned by tests/test_alerts.py, the same
guard discipline as goodput/reqtrace).

Rule taxonomy:

- **ThresholdRule** — value vs a fixed target, with optional
  `window_s` turning a cumulative counter into a per-second rate
  first.  `clear` gives hysteresis: a firing rule only un-breaches
  once the value crosses the clear threshold (not merely the firing
  one), so a value oscillating around the target cannot flap.
- **BurnRateRule** — multi-window error-budget burn for ratio SLOs
  (bad/total counters, e.g. failovers per submitted request): fires
  only when the burn factor exceeds the threshold over BOTH the long
  and the short window (the SRE multiwindow recipe — the long window
  keeps one spike from paging, the short window makes recovery
  resolve fast).
- **AnomalyRule** — z-score vs a rolling baseline (loss spikes,
  grad-norm excursions, throughput regression via `rate=True`).  The
  baseline stops absorbing samples while the rule fires, so a
  sustained regression cannot normalize itself away.

Every rule walks a pending → firing → resolved state machine gated by
`for_duration_s` (a breach must persist before firing) and
`resolve_duration_s` (a clear must persist before resolving);
transitions emit registered `alert_*` events into the `RunEventLog`,
the engine exports an `alerts` collector family for `/metrics`, serves
a JSON view on the `/alerts` route, and `signals()` returns the
rule-id → {firing, value, target} map shaped for the future
autoscaler (ROADMAP item 1: replicas added/removed by queue_wait vs
TPOT SLOs).

`fleet_rule_pack` / `trainer_rule_pack` / `serving_rule_pack` are the
default packs `Fleet.enable_alerts()` / `Trainer.enable_alerts()` /
`ServingEngine.enable_alerts()` install.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .registry import MetricFamily, MetricsRegistry, counter, gauge

ALERT_STATES = ("inactive", "pending", "firing")
_OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


# ---------------------------------------------------------------------------
# Reading values out of a MetricsRegistry snapshot
# ---------------------------------------------------------------------------

def snapshot_value(snapshot: Dict[str, Any], family: str,
                   labels: Optional[Dict[str, Any]] = None,
                   percentile: Optional[float] = None
                   ) -> Optional[float]:
    """Extract one scalar from a `MetricsRegistry.snapshot()` dict.

    `labels` filters samples (subset match).  For histogram families
    `percentile` (0-100) is read off the cumulative buckets — the same
    log-bin edges Prometheus scrapes, so an alert threshold and a
    dashboard query agree bin for bin.  Counters with several matching
    samples sum (the Prometheus aggregation); gauges average.  Returns
    None when the family/sample does not exist yet — "no data", which
    the state machine treats as neither breach nor clear.
    """
    fam = snapshot.get(family)
    if fam is None:
        return None
    want = labels or {}
    matched = [s for s in fam["samples"]
               if all(str(s["labels"].get(k)) == str(v)
                      for k, v in want.items())]
    if not matched:
        return None
    if fam["kind"] == "histogram":
        if percentile is None:
            raise ValueError(
                f"{family} is a histogram; pass percentile=")
        # samples with several label sets (e.g. reqtrace phases) were
        # narrowed by `labels`; merge what remains cumulatively
        count = sum(s["count"] for s in matched)
        if count == 0:
            return None
        target = max(1, math.ceil(count * percentile / 100.0))
        seen = 0
        edges: Dict[float, int] = {}
        for s in matched:
            prev = 0
            for le, cum in s["buckets"]:
                edges[le] = edges.get(le, 0) + (cum - prev)
                prev = cum
        for le in sorted(edges):
            seen += edges[le]
            if seen >= target:
                return float(le)
        return float(max(edges)) if edges else None
    vals = [s["value"] for s in matched]
    if fam["kind"] == "counter":
        return float(sum(vals))
    return float(sum(vals) / len(vals))


class MetricSelector:
    """Declarative pointer into a snapshot: family + label filter +
    optional histogram percentile."""

    def __init__(self, family: str,
                 labels: Optional[Dict[str, Any]] = None,
                 percentile: Optional[float] = None):
        self.family = family
        self.labels = dict(labels) if labels else None
        self.percentile = percentile

    def __call__(self, snapshot: Dict[str, Any]) -> Optional[float]:
        return snapshot_value(snapshot, self.family, self.labels,
                              self.percentile)

    def __repr__(self):
        parts = [self.family]
        if self.labels:
            parts.append(str(self.labels))
        if self.percentile is not None:
            parts.append(f"p{self.percentile:g}")
        return "MetricSelector(" + ", ".join(parts) + ")"


def _as_value_fn(source) -> Callable[[Dict[str, Any]], Optional[float]]:
    if isinstance(source, str):
        return MetricSelector(source)
    if callable(source):
        return source
    raise TypeError(f"rule source must be a family name, a "
                    f"MetricSelector, or a callable; got {source!r}")


class _RateTracker:
    """Windowed per-second rate of a cumulative counter: keeps (t,
    value) samples and differences against the newest sample at least
    `window_s` old (falling back to the oldest held) — two samples
    minimum, else no data."""

    def __init__(self, window_s: float):
        self.window_s = float(window_s)
        self._hist: deque = deque()

    def rate(self, now: float, value: Optional[float]
             ) -> Optional[float]:
        if value is None:
            return None
        self._hist.append((now, value))
        # keep the newest sample older than the window as the
        # reference; drop anything older than that
        ref_i = 0
        for i, (t, _) in enumerate(self._hist):
            if t <= now - self.window_s:
                ref_i = i
            else:
                break
        for _ in range(ref_i):
            self._hist.popleft()
        if len(self._hist) < 2:
            return None
        t0, v0 = self._hist[0]
        if now <= t0:
            return None
        return (value - v0) / (now - t0)


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

class AlertRule:
    """Base rule: subclasses implement `observe(snapshot, now)` →
    (value, breach, cleared); the base walks the pending → firing →
    resolved state machine with `for_duration_s` / `resolve_duration_s`
    gating.  A None value is "no data": the state is held, never
    advanced (missing metrics must not fire OR resolve anything)."""

    def __init__(self, rule_id: str, description: str = "",
                 severity: str = "page", for_duration_s: float = 0.0,
                 resolve_duration_s: float = 0.0,
                 target: Optional[float] = None):
        if not rule_id:
            raise ValueError("rule_id is required")
        self.id = rule_id
        self.description = description
        self.severity = severity
        self.for_duration_s = float(for_duration_s)
        self.resolve_duration_s = float(resolve_duration_s)
        self.target = target
        self.state = "inactive"
        self.value: Optional[float] = None
        self.since: Optional[float] = None       # state entry time
        self.fired_count = 0
        self.transitions = 0
        self._breach_since: Optional[float] = None
        self._clear_since: Optional[float] = None

    # subclasses override
    def observe(self, snapshot: Dict[str, Any], now: float
                ) -> Tuple[Optional[float], bool, bool]:
        raise NotImplementedError

    def step(self, snapshot: Dict[str, Any], now: float
             ) -> Optional[str]:
        """One evaluation; returns the transition event kind emitted
        ('alert_pending' / 'alert_firing' / 'alert_resolved') or
        None."""
        value, breach, cleared = self.observe(snapshot, now)
        self.value = value
        if value is None:
            return None  # no data: hold state
        transition = None
        if self.state in ("inactive",):
            if breach:
                self._breach_since = (self._breach_since
                                      if self._breach_since is not None
                                      else now)
                if now - self._breach_since >= self.for_duration_s:
                    self.state = "firing"
                    self.since = now
                    self.fired_count += 1
                    transition = "alert_firing"
                elif self.state != "pending":
                    self.state = "pending"
                    self.since = now
                    transition = "alert_pending"
            else:
                self._breach_since = None
        elif self.state == "pending":
            if breach:
                if now - self._breach_since >= self.for_duration_s:
                    self.state = "firing"
                    self.since = now
                    self.fired_count += 1
                    transition = "alert_firing"
            else:
                self._breach_since = None
                self.state = "inactive"
                self.since = now
        elif self.state == "firing":
            if cleared:
                self._clear_since = (self._clear_since
                                     if self._clear_since is not None
                                     else now)
                if now - self._clear_since >= self.resolve_duration_s:
                    self.state = "inactive"
                    self.since = now
                    self._breach_since = None
                    self._clear_since = None
                    transition = "alert_resolved"
            else:
                self._clear_since = None
        if transition:
            self.transitions += 1
        return transition

    @property
    def firing(self) -> bool:
        return self.state == "firing"

    def as_dict(self) -> Dict[str, Any]:
        return {"id": self.id, "state": self.state,
                "firing": self.firing, "value": self.value,
                "target": self.target, "severity": self.severity,
                "description": self.description, "since": self.since,
                "fired_count": self.fired_count}


class ThresholdRule(AlertRule):
    """value `op` threshold, with optional counter→rate conversion and
    a hysteresis `clear` threshold.

        ThresholdRule("ttft_p99",
                      MetricSelector("serving_ttft_ms", percentile=99),
                      op=">", threshold=500.0, clear=400.0,
                      for_duration_s=30.0)
        ThresholdRule("compile_storm", "runtime_retraces_total",
                      op=">", threshold=0.2, window_s=60.0)  # retraces/s
    """

    def __init__(self, rule_id: str, source, op: str = ">",
                 threshold: float = 0.0,
                 clear: Optional[float] = None,
                 window_s: Optional[float] = None, **kw):
        if op not in _OPS:
            raise ValueError(f"op must be one of {sorted(_OPS)}")
        kw.setdefault("target", float(threshold))
        super().__init__(rule_id, **kw)
        self.value_fn = _as_value_fn(source)
        self.op = op
        self.threshold = float(threshold)
        self.clear = float(clear) if clear is not None else None
        self._rate = _RateTracker(window_s) if window_s else None

    def observe(self, snapshot, now):
        raw = self.value_fn(snapshot)
        value = (self._rate.rate(now, raw) if self._rate is not None
                 else raw)
        if value is None:
            return None, False, False
        breach = _OPS[self.op](value, self.threshold)
        if self.clear is None:
            return value, breach, not breach
        # hysteresis: clearing requires crossing the clear threshold
        # in the non-breach direction, not merely un-breaching
        cleared = not _OPS[self.op](value, self.clear)
        return value, breach, cleared


class BurnRateRule(AlertRule):
    """Multi-window error-budget burn over a bad/total counter pair.

    bad_ratio(w) = Δbad / Δtotal over window w; burn = bad_ratio/slo.
    Breaches when burn >= `burn_factor` over BOTH `long_window_s` and
    `short_window_s`; clears when the short window drops back under.
    Reported value = the long-window burn factor."""

    def __init__(self, rule_id: str, bad, total, slo: float,
                 burn_factor: float = 1.0,
                 long_window_s: float = 300.0,
                 short_window_s: float = 30.0, **kw):
        if slo <= 0:
            raise ValueError("slo must be a positive bad-event budget "
                             "fraction")
        kw.setdefault("target", float(burn_factor))
        super().__init__(rule_id, **kw)
        self.bad_fn = _as_value_fn(bad)
        self.total_fn = _as_value_fn(total)
        self.slo = float(slo)
        self.burn_factor = float(burn_factor)
        self.windows = {"long": float(long_window_s),
                        "short": float(short_window_s)}
        self._hist: deque = deque()

    def _burn(self, now: float, window_s: float) -> Optional[float]:
        ref = None
        for t, bad, tot in self._hist:
            if t <= now - window_s:
                ref = (t, bad, tot)
            else:
                break
        if ref is None:
            ref = self._hist[0]
        t0, bad0, tot0 = ref
        cur_t, cur_bad, cur_tot = self._hist[-1]
        if cur_t <= t0 or cur_tot <= tot0:
            return None  # no traffic in the window: no data
        return ((cur_bad - bad0) / (cur_tot - tot0)) / self.slo

    def observe(self, snapshot, now):
        bad = self.bad_fn(snapshot)
        tot = self.total_fn(snapshot)
        if bad is None or tot is None:
            return None, False, False
        self._hist.append((now, bad, tot))
        horizon = now - max(self.windows.values())
        while len(self._hist) > 2 and self._hist[1][0] <= horizon:
            self._hist.popleft()
        burns = {name: self._burn(now, w)
                 for name, w in self.windows.items()}
        if burns["long"] is None:
            return None, False, False
        breach = all(b is not None and b >= self.burn_factor
                     for b in burns.values())
        cleared = (burns["short"] is None
                   or burns["short"] < self.burn_factor)
        return burns["long"], breach, cleared


class AnomalyRule(AlertRule):
    """z-score vs a rolling baseline of this rule's own past samples.

    direction: "above" (loss spike), "below" (throughput regression),
    or "both" (grad-norm excursion).  `rate=True` differences a
    cumulative counter into a per-second rate first (`window_s` sets
    the differencing window).  The baseline stops absorbing samples
    while firing, so a sustained anomaly cannot normalize itself.
    Reported value = the z-score."""

    def __init__(self, rule_id: str, source, z: float = 4.0,
                 direction: str = "above", min_samples: int = 5,
                 baseline: int = 64, rate: bool = False,
                 window_s: float = 30.0, min_std: float = 1e-9, **kw):
        if direction not in ("above", "below", "both"):
            raise ValueError("direction must be above/below/both")
        kw.setdefault("target", float(z))
        super().__init__(rule_id, **kw)
        self.value_fn = _as_value_fn(source)
        self.z = float(z)
        self.direction = direction
        self.min_samples = int(min_samples)
        self.min_std = float(min_std)
        self._rate = _RateTracker(window_s) if rate else None
        self._baseline: deque = deque(maxlen=int(baseline))
        self.sample: Optional[float] = None  # last raw sample

    def observe(self, snapshot, now):
        raw = self.value_fn(snapshot)
        value = (self._rate.rate(now, raw) if self._rate is not None
                 else raw)
        if value is None:
            return None, False, False
        self.sample = value
        if len(self._baseline) < self.min_samples:
            self._baseline.append(value)
            return 0.0, False, True
        mean = sum(self._baseline) / len(self._baseline)
        var = (sum((v - mean) ** 2 for v in self._baseline)
               / len(self._baseline))
        std = max(math.sqrt(var), self.min_std)
        score = (value - mean) / std
        if self.direction == "above":
            breach = score > self.z
        elif self.direction == "below":
            breach = score < -self.z
        else:
            breach = abs(score) > self.z
        if not (breach or self.state == "firing"):
            self._baseline.append(value)
        zval = (abs(score) if self.direction == "both"
                else score if self.direction == "above" else -score)
        return zval, breach, not breach


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class AlertEngine:
    """Evaluates rules over `registry.snapshot()` — synchronously via
    `evaluate()` or on a background daemon thread (`start()`).

    - transitions emit `alert_pending`/`alert_firing`/`alert_resolved`
      events into `event_log` (registered kinds, strict-mode clean);
    - `collector()` is the `alerts` MetricFamily source for /metrics
      (register it on the same registry — it reads rule state, it does
      not re-evaluate);
    - `state()` is the `/alerts` JSON body; `signals()` the autoscaler
      view (rule id → firing + value vs target);
    - `add_firing_hook(fn)`: fn(rule, record) runs on every firing
      transition (the FlightRecorder attaches here).

    Pure host: the only data source is the registry snapshot — zero
    device dispatches from this thread, ever."""

    def __init__(self, registry: MetricsRegistry,
                 rules: Sequence[AlertRule] = (),
                 interval_s: float = 5.0, event_log=None,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = registry
        self.interval_s = float(interval_s)
        self.event_log = event_log
        self.clock = clock
        self._lock = threading.RLock()
        self._rules: Dict[str, AlertRule] = {}
        self._firing_hooks: List[Callable[[AlertRule, Dict[str, Any]],
                                          None]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.evaluations = 0
        self.last_eval_ts: Optional[float] = None
        self.eval_errors = 0
        for r in rules:
            self.add_rule(r)

    # -- rule management ------------------------------------------------
    def add_rule(self, rule: AlertRule) -> "AlertEngine":
        with self._lock:
            if rule.id in self._rules:
                raise ValueError(f"duplicate rule id {rule.id!r}")
            self._rules[rule.id] = rule
        return self

    def remove_rule(self, rule_id: str) -> None:
        with self._lock:
            self._rules.pop(rule_id, None)

    @property
    def rules(self) -> List[AlertRule]:
        with self._lock:
            return [self._rules[k] for k in sorted(self._rules)]

    def add_firing_hook(self, fn: Callable[[AlertRule, Dict[str, Any]],
                                           None]) -> None:
        with self._lock:
            self._firing_hooks.append(fn)

    # -- evaluation -----------------------------------------------------
    def evaluate(self, now: Optional[float] = None,
                 snapshot: Optional[Dict[str, Any]] = None
                 ) -> List[Tuple[AlertRule, str]]:
        """One pass: pull a snapshot, step every rule, emit transition
        events, run firing hooks.  Returns [(rule, transition), ...]
        for this pass.  `now`/`snapshot` are injectable for tests and
        replay."""
        now = self.clock() if now is None else now
        if snapshot is None:
            try:
                snapshot = self.registry.snapshot()
            except Exception:  # noqa: BLE001 — a sick registry must not
                self.eval_errors += 1  # kill the alert thread
                return []
        transitions: List[Tuple[AlertRule, str]] = []
        with self._lock:
            rules = list(self._rules.values())
            hooks = list(self._firing_hooks)
        for rule in rules:
            try:
                kind = rule.step(snapshot, now)
            except Exception:  # noqa: BLE001 — one bad rule is isolated
                self.eval_errors += 1
                continue
            if kind is None:
                continue
            record = {"rule": rule.id, "state": rule.state,
                      "value": rule.value, "target": rule.target,
                      "severity": rule.severity,
                      "description": rule.description}
            transitions.append((rule, kind))
            if self.event_log is not None:
                try:
                    self.event_log.event(kind, **record)
                except Exception:  # noqa: BLE001
                    pass
            if kind == "alert_firing":
                for fn in hooks:
                    try:
                        fn(rule, dict(record))
                    except Exception:  # noqa: BLE001 — hooks are
                        pass           # best-effort diagnostics
        with self._lock:
            self.evaluations += 1
            self.last_eval_ts = time.time()
        return transitions

    # -- background thread ----------------------------------------------
    def start(self) -> "AlertEngine":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.evaluate()

        self._thread = threading.Thread(
            target=loop, name="alert-engine", daemon=True)
        self._thread.start()
        return self

    @property
    def running(self) -> bool:
        return self._thread is not None

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "AlertEngine":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- views -----------------------------------------------------------
    def firing(self) -> List[str]:
        return [r.id for r in self.rules if r.firing]

    def signals(self) -> Dict[str, Dict[str, Any]]:
        """The autoscaler-facing view: rule id → firing bool + current
        value vs target (+ state/severity).  A scaling policy consumes
        exactly this — e.g. add a decode replica while
        `serving_queue_wait_p99` fires, remove one while everything is
        quiet (ROADMAP item 1)."""
        return {r.id: {"firing": r.firing, "state": r.state,
                       "value": r.value, "target": r.target,
                       "severity": r.severity}
                for r in self.rules}

    def state(self) -> Dict[str, Any]:
        """The `/alerts` route body: full rule detail + engine
        counters."""
        rules = [r.as_dict() for r in self.rules]
        return {"firing": [r["id"] for r in rules if r["firing"]],
                "rules": rules,
                "evaluations": self.evaluations,
                "eval_errors": self.eval_errors,
                "interval_s": self.interval_s,
                "running": self.running,
                "last_eval_ts": self.last_eval_ts}

    def collector(self) -> Callable[[], List[MetricFamily]]:
        """The `alerts` family source for /metrics — reads rule state
        (set by the engine's own cadence), never re-evaluates, so
        registering it on the engine's OWN registry cannot recurse."""

        def collect() -> List[MetricFamily]:
            rules = self.rules
            firing = gauge("alerts_firing",
                           "1 while the rule is in the firing state")
            value = gauge("alerts_value",
                          "last evaluated rule value")
            target = gauge("alerts_target", "rule threshold/target")
            fired = counter("alerts_fired_total",
                            "lifetime firing transitions")
            for r in rules:
                lbl = {"rule": r.id, "severity": r.severity}
                firing.add(1 if r.firing else 0, **lbl)
                value.add(r.value, **lbl)
                target.add(r.target, **lbl)
                fired.add(r.fired_count, **lbl)
            return [firing, value, target, fired,
                    counter("alerts_evaluations_total",
                            "alert evaluation passes",
                            self.evaluations),
                    gauge("alerts_rules", "registered rules",
                          len(rules))]

        return collect


# ---------------------------------------------------------------------------
# Default rule packs
# ---------------------------------------------------------------------------

def fleet_rule_pack(fleet=None, *, ttft_p99_ms: float = 2000.0,
                    tpot_p99_ms: float = 200.0,
                    queue_wait_p99_ms: float = 1000.0,
                    error_slo: float = 0.01,
                    failover_window_s: float = 60.0,
                    failover_rate_per_s: float = 0.0,
                    saturated_window_s: float = 60.0,
                    for_duration_s: float = 0.0,
                    resolve_duration_s: float = 0.0
                    ) -> List[AlertRule]:
    """The serving-SLO pack `Fleet.enable_alerts()` installs.

    - `fleet_error_rate`: multiwindow burn of failed/submitted vs the
      `error_slo` budget (the paging rule).
    - `fleet_failover_rate`: ANY failover inside the window fires (a
      replica died mid-request; default threshold 0/s means one event
      trips it, and the rule resolves once the window slides past).
    - `fleet_saturated`: whole-fleet sheds observed in the window.
    - `fleet_replicas_down`: healthy_replicas below the fleet size.
    - TTFT / TPOT / queue_wait p99 thresholds from the decode-stats and
      reqtrace histograms (rules stay silent — "no data" — on fleets
      without those surfaces)."""
    kw = {"for_duration_s": for_duration_s,
          "resolve_duration_s": resolve_duration_s}
    rules = [
        BurnRateRule(
            "fleet_error_rate",
            MetricSelector("fleet_failed_total"),
            MetricSelector("fleet_submitted_total"),
            slo=error_slo, burn_factor=1.0,
            long_window_s=max(failover_window_s * 5, 300.0),
            short_window_s=failover_window_s,
            description="client-visible failure budget burning",
            **kw),
        ThresholdRule(
            "fleet_failover_rate",
            MetricSelector("fleet_failovers_total"),
            op=">", threshold=failover_rate_per_s,
            window_s=failover_window_s,
            description="in-flight requests are failing over "
                        "(a replica died mid-request)", **kw),
        ThresholdRule(
            "fleet_saturated",
            MetricSelector("fleet_saturated_total"),
            op=">", threshold=0.0, window_s=saturated_window_s,
            description="whole-fleet saturation fast-rejects",
            **kw),
        ThresholdRule(
            "serving_ttft_p99",
            MetricSelector("serving_ttft_ms", percentile=99),
            op=">", threshold=ttft_p99_ms,
            clear=ttft_p99_ms * 0.8,
            description="time-to-first-token p99 over SLO", **kw),
        ThresholdRule(
            "serving_tpot_p99",
            MetricSelector("serving_tpot_ms", percentile=99),
            op=">", threshold=tpot_p99_ms,
            clear=tpot_p99_ms * 0.8,
            description="time-per-output-token p99 over SLO", **kw),
        ThresholdRule(
            "serving_queue_wait_p99",
            MetricSelector("reqtrace_phase_ms",
                           labels={"phase": "queue_wait"},
                           percentile=99),
            op=">", threshold=queue_wait_p99_ms,
            clear=queue_wait_p99_ms * 0.8,
            description="admission queue wait p99 over SLO "
                        "(the autoscaler's scale-up signal)", **kw),
    ]
    if fleet is not None:
        rules.append(ThresholdRule(
            "fleet_replicas_down",
            MetricSelector("fleet_healthy_replicas"),
            op="<", threshold=float(len(fleet.replicas)),
            description="at least one replica is not routable",
            severity="ticket", **kw))
    return rules


def serving_rule_pack(*, e2e_p99_ms: float = 1000.0,
                      error_slo: float = 0.01,
                      window_s: float = 60.0,
                      for_duration_s: float = 0.0,
                      resolve_duration_s: float = 0.0
                      ) -> List[AlertRule]:
    """Single-engine pack (`ServingEngine.enable_alerts()`): e2e p99,
    error-budget burn over rejected+failed, and the post-warmup
    compile tripwire (ANY recompile after warmup is a bug — the PR 8
    zero-compile contract as an alert)."""
    kw = {"for_duration_s": for_duration_s,
          "resolve_duration_s": resolve_duration_s}

    def bad(snapshot):
        vals = [snapshot_value(snapshot, f"serving_{k}_total")
                for k in ("shed", "circuit_rejects",
                          "executor_failures", "deadline_misses")]
        vals = [v for v in vals if v is not None]
        return sum(vals) if vals else None

    return [
        ThresholdRule(
            "serving_e2e_p99",
            MetricSelector("serving_e2e_ms", percentile=99),
            op=">", threshold=e2e_p99_ms, clear=e2e_p99_ms * 0.8,
            description="end-to-end latency p99 over SLO", **kw),
        BurnRateRule(
            "serving_error_rate", bad,
            MetricSelector("serving_submitted_total"),
            slo=error_slo, burn_factor=1.0,
            long_window_s=max(window_s * 5, 300.0),
            short_window_s=window_s,
            description="reject+failure budget burning", **kw),
        ThresholdRule(
            "serving_post_warmup_compiles",
            MetricSelector("serving_post_warmup_compiles"),
            op=">", threshold=0.0,
            description="a shape leaked past the bucket ladder "
                        "(zero-compile contract broken)", **kw),
    ]


def disagg_rule_pack(fleet=None, *,
                     prefill_wait_p99_ms: float = 1000.0,
                     tpot_p99_ms: float = 200.0,
                     handoff_p99_ms: float = 250.0,
                     error_slo: float = 0.01,
                     window_s: float = 60.0,
                     for_duration_s: float = 0.0,
                     resolve_duration_s: float = 0.0
                     ) -> List[AlertRule]:
    """The phase-split SLO pack `DisaggFleet.enable_alerts()` installs
    — and the Autoscaler's signal source (serving/disagg.py): each
    phase scales on ITS rule, which is exactly why the pack is split
    by phase instead of reusing the joint fleet pack.

    - `disagg_prefill_wait_p99`: the prefill workers' merged TTFT
      histogram (queue wait + bucketed prefill dispatch) — the
      scale-UP-prefill signal.
    - `disagg_decode_tpot_p99`: the decode workers' merged
      time-per-output-token — the scale-UP-decode signal.
    - `disagg_handoff_p99`: export gather + router relay + import
      admission per KV hop (a slow transfer plane is its own
      pathology, not a capacity one — severity ticket).
    - `disagg_error_rate`: client-visible failure budget burn.
    - `serving_post_warmup_compiles`: ANY recompile after warmup
      anywhere in the fleet (the zero-compile contract as an alert).
    """
    kw = {"for_duration_s": for_duration_s,
          "resolve_duration_s": resolve_duration_s}
    return [
        ThresholdRule(
            "disagg_prefill_wait_p99",
            MetricSelector("disagg_prefill_wait_ms", percentile=99),
            op=">", threshold=prefill_wait_p99_ms,
            clear=prefill_wait_p99_ms * 0.8,
            description="prefill-side wait p99 over SLO (the "
                        "autoscaler's scale-up-prefill signal)", **kw),
        ThresholdRule(
            "disagg_decode_tpot_p99",
            MetricSelector("disagg_decode_tpot_ms", percentile=99),
            op=">", threshold=tpot_p99_ms,
            clear=tpot_p99_ms * 0.8,
            description="decode-side TPOT p99 over SLO (the "
                        "autoscaler's scale-up-decode signal)", **kw),
        ThresholdRule(
            "disagg_handoff_p99",
            MetricSelector("disagg_handoff_ms", percentile=99),
            op=">", threshold=handoff_p99_ms,
            clear=handoff_p99_ms * 0.8, severity="ticket",
            description="KV-page handoff latency p99 over SLO",
            **kw),
        BurnRateRule(
            "disagg_error_rate",
            MetricSelector("disagg_failed_total"),
            MetricSelector("disagg_submitted_total"),
            slo=error_slo, burn_factor=1.0,
            long_window_s=max(window_s * 5, 300.0),
            short_window_s=window_s,
            description="client-visible failure budget burning",
            **kw),
        ThresholdRule(
            "serving_post_warmup_compiles",
            MetricSelector("serving_post_warmup_compiles"),
            op=">", threshold=0.0,
            description="a recompile leaked past warmup somewhere in "
                        "the fleet (zero-compile contract broken)",
            **kw),
    ]


def speculate_rule_pack(*, min_accept_rate: float = 0.3,
                        min_efficiency: float = 0.0,
                        for_duration_s: float = 0.0,
                        resolve_duration_s: float = 0.0
                        ) -> List[AlertRule]:
    """Speculative-decoding health pack (docs/SERVING.md §speculate).

    - `serving_speculation_accept_low`: the cumulative accept rate
      dropped under `min_accept_rate`.  Below that floor the verify
      rows mostly score rejected drafts — the engine is paying the
      folded-batch cost of speculation without the multi-token wins,
      and a sequential engine (or a better drafter / smaller k) would
      serve the same stream faster.  Severity ticket: it is a
      throughput regression, not an outage.
    - `serving_speculation_efficiency_low` (opt-in via
      `min_efficiency` > 0): committed tokens over verify rows paid —
      the same signal normalized per row, useful when comparing
      different k settings across replicas.

    Rules stay silent ("no data") until the engine has scored drafts,
    so installing the pack on a non-speculative fleet is harmless.
    """
    kw = {"for_duration_s": for_duration_s,
          "resolve_duration_s": resolve_duration_s}
    rules = [
        ThresholdRule(
            "serving_speculation_accept_low",
            MetricSelector("serving_speculation_accept_rate"),
            op="<", threshold=min_accept_rate,
            clear=min_accept_rate * 1.2, severity="ticket",
            description="speculative accept rate under floor (drafts "
                        "mostly rejected — speculation is costing "
                        "throughput instead of buying it)", **kw),
    ]
    if min_efficiency > 0.0:
        rules.append(ThresholdRule(
            "serving_speculation_efficiency_low",
            MetricSelector("serving_speculation_efficiency"),
            op="<", threshold=min_efficiency,
            clear=min_efficiency * 1.2, severity="ticket",
            description="committed tokens per verify row under floor",
            **kw))
    return rules


def trainer_rule_pack(*, goodput_floor: float = 0.5,
                      loss_spike_z: float = 6.0,
                      grad_norm_z: float = 6.0,
                      throughput_drop_z: float = 4.0,
                      retrace_rate_per_s: float = 0.05,
                      retrace_window_s: float = 120.0,
                      gang_max_lag_steps: float = 50.0,
                      for_duration_s: float = 0.0,
                      resolve_duration_s: float = 0.0
                      ) -> List[AlertRule]:
    """The training-health pack `Trainer.enable_alerts()` installs.

    - `train_goodput_drop`: goodput fraction below the floor (ledger).
    - `train_throughput_regression`: steps/s z-score below the rolling
      baseline (AnomalyRule over the goodput step counter rate).
    - `train_loss_spike` / `train_grad_norm_anomaly`: z-score
      excursions of the pillar-6 telemetry window means.
    - `train_nonfinite`: any non-finite grad/loss step in the window.
    - `train_compile_storm`: retraces/s over budget — the
      feed-signature-drift storm (runtime_stats counter rate).
    - `gang_skew`: heartbeat step lag beyond the straggler budget
      (silent without a gang).
    - `train_recovery_rollbacks`: the divergence autopilot recovered
      in-run (ticket severity — nobody was paged, which is the point;
      silent without an autopilot)."""
    kw = {"for_duration_s": for_duration_s,
          "resolve_duration_s": resolve_duration_s}

    def nonfinite(snapshot):
        g = snapshot_value(snapshot,
                           "training_nonfinite_grad_steps_total")
        lo = snapshot_value(snapshot,
                            "training_nonfinite_loss_steps_total")
        vals = [v for v in (g, lo) if v is not None]
        return sum(vals) if vals else None

    return [
        ThresholdRule(
            "train_goodput_drop",
            MetricSelector("goodput_fraction_good"),
            op="<", threshold=goodput_floor,
            clear=min(goodput_floor * 1.2, 1.0),
            description="useful-step share of wall clock below "
                        "floor", **kw),
        AnomalyRule(
            "train_throughput_regression",
            MetricSelector("goodput_steps_total"),
            z=throughput_drop_z, direction="below", rate=True,
            description="steps/s regressed vs the rolling baseline",
            **kw),
        AnomalyRule(
            "train_loss_spike",
            MetricSelector("training_loss_mean"),
            z=loss_spike_z, direction="above",
            description="window-mean loss spiked vs baseline", **kw),
        AnomalyRule(
            "train_grad_norm_anomaly",
            MetricSelector("training_grad_norm_last"),
            z=grad_norm_z, direction="both",
            description="grad-norm excursion vs baseline", **kw),
        ThresholdRule(
            "train_nonfinite", nonfinite,
            op=">", threshold=0.0, window_s=retrace_window_s,
            description="non-finite grads/loss observed "
                        "(see nonfinite_provenance for the op)",
            **kw),
        ThresholdRule(
            "train_compile_storm",
            MetricSelector("runtime_retraces_total"),
            op=">", threshold=retrace_rate_per_s,
            window_s=retrace_window_s,
            description="step retrace storm (feed signature drift)",
            **kw),
        ThresholdRule(
            "gang_skew",
            MetricSelector("gang_max_lag_steps"),
            op=">", threshold=gang_max_lag_steps,
            clear=gang_max_lag_steps * 0.5,
            description="a rank lags the gang beyond the straggler "
                        "budget", severity="ticket", **kw),
        ThresholdRule(
            "train_recovery_rollbacks",
            MetricSelector("recovery_rollbacks_total"),
            op=">", threshold=0.0,
            description="the divergence autopilot rolled back to a "
                        "verified-good checkpoint (recovered in-run; "
                        "see recovery_rollback/data_quarantine "
                        "events for the window)",
            severity="ticket", **kw),
    ]
