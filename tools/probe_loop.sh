#!/usr/bin/env bash
# TPU tunnel probe loop (VERDICT r4 item 1 / outage playbook).
#
# Probes the axon backend in a SUBPROCESS with a hard timeout (the hang
# mode never raises in-process) every INTERVAL seconds, appending one
# timestamped line per probe to docs/PROBE_r05.log:
#
#   2026-07-31T02:10:11Z UP TPU_v5e_x1 (12.3s)
#   2026-07-31T02:30:12Z DOWN timeout>90s
#
# On the first UP it also touches docs/PROBE_UP.flag so a glance at the
# repo root answers "is the probe loop up and has it seen the tunnel
# alive".  The flag is removed when the loop exits (trap below): a
# stale flag must not outlive the loop as evidence (VERDICT r5) — and
# bench.py treats a FRESH flag as a live attach hazard, so cleanup also
# stops killed-loop residue from tainting later bench lines.
# Runs until killed; intended to be started detached at round start.
set -u
cd "$(dirname "$0")/.."
trap 'rm -f docs/PROBE_UP.flag' EXIT HUP INT TERM
LOG=docs/PROBE_r05.log
INTERVAL="${PROBE_INTERVAL:-1200}"
TIMEOUT="${PROBE_TIMEOUT:-90}"
while true; do
  ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  start=$(date +%s.%N)
  out=$(timeout "$TIMEOUT" python - <<'EOF' 2>&1
import jax
ds = jax.devices()
print("PROBE_OK", len(ds), ds[0].platform, getattr(ds[0], "device_kind", "?"))
EOF
)
  rc=$?
  dur=$(python -c "import time;print(f'{$(date +%s.%N)-$start:.1f}')")
  if [ $rc -eq 0 ] && printf '%s' "$out" | grep -q PROBE_OK; then
    kind=$(printf '%s' "$out" | grep PROBE_OK | awk '{print $3"_"$4"_x"$2}')
    echo "$ts UP $kind (${dur}s)" >> "$LOG"
    touch docs/PROBE_UP.flag
  elif [ $rc -eq 124 ]; then
    echo "$ts DOWN timeout>${TIMEOUT}s" >> "$LOG"
  else
    err=$(printf '%s' "$out" | tail -1 | cut -c1-120)
    echo "$ts DOWN rc=$rc $err" >> "$LOG"
  fi
  sleep "$INTERVAL"
done
