"""Gradient clipping.

reference: python/paddle/fluid/clip.py — GradientClipByValue,
GradientClipByNorm, GradientClipByGlobalNorm, set_gradient_clip.
"""

from __future__ import annotations

from typing import List, Optional, Tuple


class BaseGradientClipAttr:
    def create_operators(self, param, grad):
        raise NotImplementedError

    def process_context(self, context, param, grad):
        pass


class NullGradientClipAttr(BaseGradientClipAttr):
    def create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        if min is None:
            min = -max
        self.max, self.min = float(max), float(min)

    def create_operators(self, param, grad):
        from . import layers

        new_grad = layers.clip(grad, self.min, self.max)
        return param, _rebind(grad, new_grad)


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def create_operators(self, param, grad):
        from . import layers

        new_grad = layers.clip_by_norm(grad, self.clip_norm)
        return param, _rebind(grad, new_grad)


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Resolved group-wise by append_gradient_clip_ops below."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name


def _rebind(old_grad, new_value):
    """Route the clipped value back into the original grad var name so the
    optimizer op (which reads `<p>@GRAD`) sees it."""
    block = old_grad.block
    block.append_op(type="assign", inputs={"X": [new_value]},
                    outputs={"Out": [old_grad]})
    return old_grad


_clip_attr_default = None


def set_gradient_clip(clip, param_list=None, program=None):
    """reference clip.py set_gradient_clip — set clip attr on params (or as
    a global default)."""
    global _clip_attr_default
    if param_list:
        for p in param_list:
            p.gradient_clip_attr = clip
    else:
        _clip_attr_default = clip


def append_gradient_clip_ops(params_grads):
    from . import layers

    result = []
    global_groups: dict = {}
    for param, grad in params_grads:
        clip_attr = param.gradient_clip_attr or _clip_attr_default
        if clip_attr is None:
            result.append((param, grad))
            continue
        if isinstance(clip_attr, GradientClipByGlobalNorm):
            global_groups.setdefault(clip_attr.group_name,
                                     (clip_attr, []))[1].append((param, grad))
            continue
        result.append(clip_attr.create_operators(param, grad))

    for group_name, (clip_attr, pairs) in global_groups.items():
        sq_sum = None
        for _, grad in pairs:
            s = layers.reduce_sum(layers.elementwise_mul(grad, grad))
            sq_sum = s if sq_sum is None else layers.sums([sq_sum, s])
        global_norm = layers.sqrt(sq_sum)
        clip_var = layers.fill_constant([1], "float32", clip_attr.clip_norm)
        scale_factor = layers.elementwise_div(
            clip_var,
            layers.elementwise_max(global_norm, clip_var))
        for param, grad in pairs:
            scaled = layers.elementwise_mul(grad, scale_factor)
            result.append((param, _rebind(grad, scaled)))
    return result


class ErrorClipByValue:
    """Accepted for API parity; forward-activation error clipping is a
    no-op in whole-program AD (gradients flow through jax.grad)."""

    def __init__(self, max, min=None):
        self.max, self.min = max, min
