"""fluid.layers-equivalent namespace.

reference: python/paddle/fluid/layers/__init__.py — flat namespace over
nn / tensor / io / ops / control_flow / metric_op / learning-rate
schedulers.
"""

from .io import data  # noqa: F401
from .metric_op import accuracy, auc  # noqa: F401
from .nn import *  # noqa: F401,F403
from .nn import elementwise_op  # noqa: F401
from .ops import *  # noqa: F401,F403
from .tensor import (argmax, argmin, argsort, assign, cast, concat,  # noqa: F401
                     create_global_var, create_tensor, fill_constant,
                     fill_constant_batch_size_like, increment, isfinite,
                     ones, range, reverse, sums, where, zeros, zeros_like)
